"""Out-of-core sharded tree learner.

Grows exactly the serial learner's trees over a
:class:`~..io.shards.ShardedBinnedDataset` whose binned rows never sit
in device memory all at once: every histogram pass is an ordered sweep
over memory-mapped shards, each staged into HBM by the double-buffered
:class:`~..io.shards.ShardPrefetcher` while the previous shard
computes.

Bit-parity contract (pinned by tests/test_shards.py): trees are
BIT-IDENTICAL to :class:`~.serial.SerialTreeLearner` on the same rows
because

- gh staging, feature sampling, split scans (``find_best_split``),
  candidate bookkeeping (``_finish_split``/``_store_info``) and the
  split-record replay are the serial learner's own functions, reused;
- per-leaf histograms accumulate shard-by-shard through an ORDERED
  scatter-add (``acc.at[flat].add``) whose update order is the global
  ascending row order — on scatter backends (CPU auto-selects the
  segment-sum scatter path) this is the very same sequence of f32 adds
  the serial learner's single-pass ``segment_sum`` performs, and under
  quantized integer gradients the accumulation is exact int32/int64
  arithmetic, order-invariant on every backend;
- the per-tree quantization scale is ``max|g|`` over the full
  device-resident gradient vector — identical to the serial staging —
  so quantized rows are drawn bit-identically.

Per-row O(1)-width state (the [R, 4] gh rows, per-shard row→leaf
segments) stays device-resident: O(N) words next to the O(N·F)-byte
bins payload the shards stream. The device argmax that picks the next
leaf is read back once per split (the documented JLT001 sync, like the
serial learner's per-batch read-back) — so a tree costs
``num_leaves`` shard sweeps. Batching K splits per sweep is the
standing follow-up (ROADMAP).

Unsupported here (loud ``log.fatal`` at setup): CEGB, the
intermediate/advanced monotone methods (``basic`` works — it lives
inside the split scan), forced splits, interaction constraints /
per-node column sampling, linear trees, EFB (the sharded dataset never
bundles).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..io.shards import ShardedBinnedDataset, ShardPrefetcher
from ..models.tree import Tree
from ..obs import compile as obs_compile
from ..obs.registry import registry as obs
from ..ops.histogram import mask_gh, resolve_hist_impl, subtract_histogram
from ..ops.quantize import acc_dtype, dequantize_sums, sum_gh
from ..ops.split import (FeatureMeta, SplitParams, calculate_leaf_output,
                         find_best_split, pad_feature_meta,
                         select_frontier)
from ..utils import log, next_pow2 as _next_pow2
from ..utils.scalars import dev_bool, dev_i32
from .capabilities import CapabilityMixin
from .serial import (_finish_split, _go_left_by_bin, _maybe_rand_bins,
                     _pad_rows_fn_cached, _record_at, _stage_gh_fn_cached,
                     apply_split_record, make_root_state, rec_valid,
                     record_is_valid)


def _accum_hist(hist: jnp.ndarray, bins: jnp.ndarray,
                gh: jnp.ndarray) -> jnp.ndarray:
    """Ordered scatter-add of one shard's rows into the running
    [F, B, C] accumulator. The flat-index + broadcast layout matches
    ops/histogram._segment_histogram exactly, and seeding the scatter
    with the RUNNING accumulator (instead of summing per-shard partials)
    is what keeps the f32 result bit-identical to the serial learner's
    single segment-sum pass: the adds land in the same global row
    order. Rows with gh == 0 (shard pad, rows outside the leaf) vanish
    from every sum."""
    S, F = bins.shape
    B = hist.shape[1]
    C = gh.shape[1]
    flat = (jnp.arange(F, dtype=jnp.int32)[None, :] * B
            + bins.astype(jnp.int32)).reshape(-1)
    vals = jnp.broadcast_to(
        gh.astype(hist.dtype)[:, None, :], (S, F, C)).reshape(-1, C)
    return hist.reshape(F * B, C).at[flat].add(vals).reshape(F, B, C)


@functools.lru_cache(maxsize=None)
def _zero_hist_fn_cached(Fp: int, B: int, dtype_name: str):
    """Fresh [Fp, B, 4] accumulator per sweep, produced on device by a
    jitted constant (an eager ``jnp.zeros`` would be an implicit
    host→device transfer per tree — the sanitizer pins this)."""
    dtype = jnp.dtype(dtype_name)

    def zero():
        return jnp.zeros((Fp, B, 4), dtype=dtype)

    return obs_compile.instrument_jit("sharded.zero_hist", zero)


_sum_gh_fn = obs_compile.instrument_jit("sharded.sum_gh", sum_gh)


@functools.lru_cache(maxsize=None)
def _gh_seg_fn_cached(n_k: int, n_pad: int):
    """Slice one shard's [n_pad, 4] gh segment (trailing zero pad rows)
    out of the full padded gh matrix; the pad row is the shard gather's
    fill target."""
    def seg(gh_full, offset):
        part = jax.lax.dynamic_slice(
            gh_full, (offset, jnp.int32(0)), (n_k, gh_full.shape[1]))
        return jnp.concatenate(
            [part, jnp.zeros((n_pad - n_k, gh_full.shape[1]),
                             dtype=part.dtype)], axis=0)

    return obs_compile.instrument_jit("sharded.gh_seg", seg)


@functools.lru_cache(maxsize=None)
def _root_fn_cached(L: int, B: int, extra_trees: bool, has_cat: bool):
    """Root split scan over the swept histogram — the tail of the
    serial learner's ``_root_fn`` with the histogram (and the channel
    sums) computed outside."""
    def root(hist, sums_raw, gh0, leaf0, feature_mask, children_allowed,
             rand_seed, qscale, meta, params):
        F = meta.num_bin.shape[0]
        sums = dequantize_sums(sums_raw, qscale)
        parent_out = calculate_leaf_output(sums[0], sums[1], params)
        info = find_best_split(
            hist, sums[0], sums[1], sums[2], sums[3], meta, params,
            feature_mask, parent_output=parent_out,
            rand_bins=_maybe_rand_bins(extra_trees, rand_seed, 0, meta,
                                       params),
            leaf_depth=jnp.int32(0), has_categorical=has_cat,
            hist_scale=qscale)
        state = make_root_state(gh0, hist, leaf0, info, L, F, B,
                                children_allowed)
        return state, _record_at(state, 0)

    return obs_compile.instrument_jit("sharded.root", root)


def _shard_step(shard_bins, leaf_seg, gh_seg, hist, rec, new_leaf, meta,
                S: int):
    """One shard's slice of a split step: route the shard's rows of the
    split leaf left/right (the serial ``_split_body`` partition update,
    applied to this contiguous row segment), then gather the rows now
    sitting on the SMALLER child and scatter them into the running
    child histogram. Shard segments are disjoint contiguous row ranges,
    so sweeping them in order performs the identical per-row updates —
    and the identical ordered histogram adds — as the serial learner's
    full-array pass.

    ``S`` is the STATIC gather width: a power-of-two bucket of the
    smaller child's global row count (an upper bound on any shard's
    share of it), the same trick the serial learner's ``_bucket`` uses
    to keep deep-tree steps from scanning all rows. Fill rows hit the
    shard's zero pad row (gh 0), so the bucket size changes compiled
    variants, never values."""
    n_pad = shard_bins.shape[0]
    leaf = rec.leaf
    f = jnp.maximum(rec.feature, 0)
    col = jnp.take(shard_bins, f, axis=1).astype(jnp.int32)
    gl = _go_left_by_bin(col, rec.threshold_bin, rec.default_left,
                         meta.missing_type[f], meta.num_bin[f] - 1,
                         meta.zero_bin[f], rec.is_categorical,
                         rec.cat_mask)
    on_leaf = leaf_seg == leaf
    leaf_seg = jnp.where(on_leaf & ~gl, new_leaf, leaf_seg)
    smaller_is_left = rec.left_total_count <= rec.right_total_count
    small_id = jnp.where(smaller_is_left, leaf, new_leaf)
    (idx,) = jnp.nonzero(leaf_seg == small_id, size=S,
                         fill_value=n_pad - 1)
    hist = _accum_hist(hist, shard_bins[idx], gh_seg[idx])
    return leaf_seg, hist


_shard_step_fn = obs_compile.instrument_jit(
    "sharded.shard_step", _shard_step, static_argnums=(7,))

# gather-bucket floor: caps compiled shard-step variants (serial's
# _MIN_BUCKET discipline)
_MIN_BUCKET = 256


@functools.lru_cache(maxsize=None)
def _finish_fn_cached(B: int, max_depth: int, extra_trees: bool,
                      has_cat: bool):
    """Split-step tail after the shard sweep: sibling subtraction from
    the parent's stored histogram, per-leaf store updates and both
    children's best-split scans (``_finish_split``, shared verbatim
    with the serial learner), then the device argmax that names the
    next split."""
    def finish(state, rec, new_leaf, hist_small, feature_mask,
               rand_seed, qscale, meta, params):
        leaf = rec.leaf
        smaller_is_left = rec.left_total_count <= rec.right_total_count
        hist_large = subtract_histogram(state.hists[leaf], hist_small)
        hist_left = jnp.where(smaller_is_left, hist_small, hist_large)
        hist_right = jnp.where(smaller_is_left, hist_large, hist_small)
        hists = state.hists.at[leaf].set(hist_left) \
            .at[new_leaf].set(hist_right)
        state = state._replace(hists=hists)
        state = _finish_split(state, rec, leaf, new_leaf,
                              jnp.asarray(True), hist_left, hist_right,
                              feature_mask, feature_mask, meta, params,
                              max_depth=max_depth,
                              extra_trees=extra_trees, has_cat=has_cat,
                              rand_seed=rand_seed, qscale=qscale)
        best = jnp.argmax(state.gain).astype(jnp.int32)
        return state, _record_at(state, best)

    return obs_compile.instrument_jit("sharded.finish", finish,
                                      donate_argnums=(0,))


# ----------------------------------------------------------------------
# K-splits-per-sweep frontier batching. One shard staging serves up to
# K pending splits: the round SPECULATES the top-K best-split
# candidates of the current store (slot 0 pinned to the argmax —
# ops/split.py select_frontier), applies all K partition routings and
# histograms all K smaller children in a single sweep, then a
# device-side finish VALIDATES the leaf-wise order split by split —
# a speculated slot is accepted only while the store argmax still
# names it, exactly reproducing the sequential grower's choices (a
# freshly-scanned child that out-gains the next pending candidate
# rejects the tail). Rejected slots' partition routings are reverted
# at the next staging (their new-leaf ids are about to be reused), and
# their histograms are discarded — wasted compute, but the staging
# traffic (the out-of-core bottleneck) is paid ONCE per round instead
# of once per split. Trees stay BIT-identical to serial growth:
# accepted splits perform the identical ordered scatter-adds and scans
# the one-split-per-sweep path performs.
# ----------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _zero_khist_fn_cached(K: int, Fp: int, B: int, dtype_name: str):
    """Fresh [K, Fp, B, 4] per-slot accumulator block per sweep round
    (jitted constant, like _zero_hist_fn_cached)."""
    dtype = jnp.dtype(dtype_name)

    def zero():
        return jnp.zeros((K, Fp, B, 4), dtype=dtype)

    return obs_compile.instrument_jit("sharded.zero_khist", zero)


def _slot(recs, i: int):
    """Record ``i`` of a [K]-stacked SplitRecord."""
    return jax.tree_util.tree_map(lambda a: a[i], recs)


def _spec_records(state, K: int):
    """Stacked top-K speculation records. The record gain carries the
    SELECTION value from select_frontier — -inf on dead slots even
    when their index aliases a live leaf — so host
    ``record_is_valid`` and device ``rec_valid`` both reject exactly
    the slots the selection did not really pick."""
    leaves, vals = select_frontier(state.gain, K)
    return _record_at(state, leaves)._replace(gain=vals)


def _shard_kstep(shard_bins, leaf_seg, gh_seg, hists, recs,
                 new_leaf_base, spec_valid, revert_from, revert_to,
                 meta, K: int, S: int):
    """One shard's slice of a K-split sweep round.

    1. revert the previous round's REJECTED routings (their new-leaf
       ids are reused by this round's slots, so this must precede the
       new updates); ``revert_from`` is -1 on non-rejected slots, and
       the explicit ``>= 0`` guard keeps the -1 sentinel from
       matching the pad rows' leaf -1;
    2. apply the K speculated partition updates — the speculated
       leaves are distinct (one pending candidate per leaf), so the
       updates commute and match the sequential per-split routing;
    3. gather + scatter each slot's smaller child into its running
       histogram. Child ``i``'s membership is unaffected by the other
       slots' routings (distinct source and target leaf ids), so the
       gathered rows — and the ordered adds — are exactly the
       sequential sweep's.

    ``S`` is one static gather bucket for all K slots (the max of the
    slots' smaller-child buckets, host-chosen); fill rows hit the
    shard's zero pad row."""
    n_pad = shard_bins.shape[0]
    leaf_seg = _apply_reverts(leaf_seg, revert_from, revert_to, K)
    for i in range(K):
        rec = _slot(recs, i)
        f = jnp.maximum(rec.feature, 0)
        col = jnp.take(shard_bins, f, axis=1).astype(jnp.int32)
        gl = _go_left_by_bin(col, rec.threshold_bin, rec.default_left,
                             meta.missing_type[f], meta.num_bin[f] - 1,
                             meta.zero_bin[f], rec.is_categorical,
                             rec.cat_mask)
        on_leaf = leaf_seg == rec.leaf
        leaf_seg = jnp.where(spec_valid[i] & on_leaf & ~gl,
                             new_leaf_base + i, leaf_seg)
    for i in range(K):
        rec = _slot(recs, i)
        smaller_is_left = rec.left_total_count <= rec.right_total_count
        small_id = jnp.where(smaller_is_left, rec.leaf,
                             new_leaf_base + i)
        (idx,) = jnp.nonzero(leaf_seg == small_id, size=S,
                             fill_value=n_pad - 1)
        # invalid slots still gather (static shapes) but their rows are
        # zeroed so the slot histogram stays null
        gh_rows = mask_gh(gh_seg[idx], spec_valid[i])
        hists = hists.at[i].set(
            _accum_hist(hists[i], shard_bins[idx], gh_rows))
    return leaf_seg, hists


_shard_kstep_fn = obs_compile.instrument_jit(
    "sharded.shard_kstep", _shard_kstep, static_argnums=(10, 11))


def _apply_reverts(leaf_seg, revert_from, revert_to, K: int):
    """Undo the previous round's rejected routings on one shard
    segment. ``revert_from`` is -1 on non-rejected slots; the explicit
    ``>= 0`` guard keeps the sentinel from matching the pad rows' leaf
    -1. Shared by the in-sweep revert (``_shard_kstep``) and the
    post-loop cleanup (``_revert_fn_cached``) — the two MUST apply
    identical rules or the partition handed to the score update
    desyncs from what the next sweep assumed."""
    for j in range(K):
        hit = (revert_from[j] >= 0) & (leaf_seg == revert_from[j])
        leaf_seg = jnp.where(hit, revert_to[j], leaf_seg)
    return leaf_seg


@functools.lru_cache(maxsize=None)
def _revert_fn_cached(K: int):
    """Standalone revert of rejected routings — applied to every shard
    segment after the grow loop ends with rejections still pending
    (no further sweep will fold the revert in)."""
    def revert(leaf_seg, revert_from, revert_to):
        return _apply_reverts(leaf_seg, revert_from, revert_to, K)

    return obs_compile.instrument_jit("sharded.revert", revert)


@functools.lru_cache(maxsize=None)
def _kfinish_fn_cached(B: int, K: int, max_depth: int, extra_trees: bool,
                       has_cat: bool):
    """Validated finish of one K-split sweep round: slot by slot —
    check the store argmax still names the speculated leaf (the
    sequential grower's choice), then masked sibling subtraction +
    per-leaf store updates + both children's scans (the shared
    ``_finish_split`` tail). The first rejected slot kills the rest of
    the round (`alive` chain): their state writes are suppressed and
    the host reverts their routings next staging. Returns the
    accepted mask; the NEXT round's speculation comes from the
    separate gather-only ``_spec_fn`` dispatch (an in-jit epilogue
    was measured to shift the scans' f32 sums an ulp off the
    one-split compile — see ``_spec_fn_cached``)."""
    def kfinish(state, recs, hists, new_leaf_base, spec_valid,
                feature_mask, rand_seed, qscale, meta, params):
        accepted = jnp.zeros(K, dtype=bool)
        alive = jnp.asarray(True)
        for i in range(K):
            # barrier between slots: each slot's subtraction + child
            # scans must compile like the one-split finish dispatch —
            # cross-slot fusion is free to contract a dequantize
            # multiply into an FMA and drift the stored gains by an
            # ulp off the stepped path (the train_many precedent)
            state = jax.lax.optimization_barrier(state)
            rec = _slot(recs, i)
            is_next = (jnp.argmax(state.gain).astype(jnp.int32)
                       == rec.leaf)
            ok = alive & spec_valid[i] & is_next & rec_valid(rec)
            new_leaf = (new_leaf_base + i).astype(jnp.int32)
            leaf = rec.leaf
            smaller_is_left = (rec.left_total_count
                               <= rec.right_total_count)
            hist_small = hists[i]
            hist_large = subtract_histogram(state.hists[leaf],
                                            hist_small)
            hist_left = jnp.where(smaller_is_left, hist_small,
                                  hist_large)
            hist_right = jnp.where(smaller_is_left, hist_large,
                                   hist_small)
            hs = state.hists \
                .at[leaf].set(jnp.where(ok, hist_left,
                                        state.hists[leaf])) \
                .at[new_leaf].set(jnp.where(ok, hist_right,
                                            state.hists[new_leaf]))
            state = state._replace(hists=hs)
            state = _finish_split(state, rec, leaf, new_leaf, ok,
                                  hist_left, hist_right, feature_mask,
                                  feature_mask, meta, params,
                                  max_depth=max_depth,
                                  extra_trees=extra_trees,
                                  has_cat=has_cat, rand_seed=rand_seed,
                                  qscale=qscale)
            accepted = accepted.at[i].set(ok)
            alive = ok
        return state, accepted

    return obs_compile.instrument_jit("sharded.kfinish", kfinish,
                                      donate_argnums=(0,))


@functools.lru_cache(maxsize=None)
def _spec_fn_cached(K: int):
    """Top-K speculation records off an existing GrowState — pure
    gathers (select_frontier + _record_at), no split math. Runs as its
    OWN dispatch after the shared ``_root_fn``: compiling a combined
    root+spec program was measured to shift the root scan's f32
    cumsum sums by an ulp against the one-split path (XLA refuses the
    same contraction choices under a different epilogue), breaking
    bit parity; a gather-only follow-up dispatch cannot."""
    def spec_of(state):
        return _spec_records(state, K)

    return obs_compile.instrument_jit("sharded.spec", spec_of)


@functools.lru_cache(maxsize=None)
def _rows_out_fn_cached(sizes: tuple):
    """Per-shard leaf segments → the full [N] row→leaf vector the
    boosting layer's score update gathers over."""
    def rows_out(*segs):
        return jnp.concatenate([s[:n] for s, n in zip(segs, sizes)])

    return obs_compile.instrument_jit("sharded.rows_out", rows_out)


class ShardedTreeLearner(CapabilityMixin):
    """Leaf-wise grower over memory-mapped binned shards."""

    def __init__(self, config, dataset: ShardedBinnedDataset):
        self.config = config
        self.dataset = dataset
        N = dataset.num_data
        F = dataset.num_features
        if F == 0:
            log.fatal("Cannot train without features")
        self.N, self.F = N, F
        # identical canonical geometry to the serial learner — part of
        # the bit-parity contract (gh padding enters the channel sums)
        self.B = _next_pow2(max(int(dataset.max_num_bin), 2))
        self.L = int(config.num_leaves)
        self.max_depth = int(config.max_depth)
        self.R = -(-(N + 1) // 4096) * 4096
        self.Fp = -(-F // 8) * 8
        self._check_unsupported(config)
        qbits = (int(getattr(config, "quant_grad_bits", 8))
                 if getattr(config, "use_quantized_grad", False) else 0)
        hist_impl = resolve_hist_impl(
            getattr(config, "hist_backend", "auto"),
            bool(getattr(config, "tpu_use_f64_hist", False)), qbits)
        if hist_impl[1]:
            log.warning("tpu_use_f64_hist is ignored on the sharded "
                        "path (f32 ordered-scatter accumulation)")
        self._init_quantization(hist_impl[2], config, N)
        if not self._quantized and jax.default_backend() != "cpu":
            log.warning("sharded exact-f32 training off a scatter "
                        "backend: histogram accumulation order may "
                        "differ from the in-memory learner "
                        "(use_quantized_grad is order-invariant "
                        "everywhere)")
        self.meta = pad_feature_meta(
            FeatureMeta.from_dataset(dataset,
                                     int(config.max_cat_to_onehot)),
            self.Fp - F)
        self.params = SplitParams.from_config(config)
        self._ff_rng = np.random.RandomState(config.feature_fraction_seed)
        self._resolve_constraints()
        self._extra_trees = bool(config.extra_trees)
        self._extra_seed = int(config.extra_seed)
        self._tree_idx = 0
        self._has_cat = bool(np.asarray(self.meta.is_categorical).any())
        self._hist_dtype = (np.dtype(acc_dtype(self._qdtype)).name
                            if self._quantized else "float32")
        self._ones_ind = jnp.ones(N, dtype=jnp.float32)
        # per-shard geometry + the device-resident per-shard row→leaf
        # segments' initial value (pad row = -1, never a real leaf)
        self.prefetcher = ShardPrefetcher(dataset, self.Fp)
        self._offsets = [int(o) for o in dataset.shard_offsets]
        self._sizes = [int(s) for s in dataset.shard_sizes]
        self._pads = [n + 1 for n in self._sizes]
        self._leaf_seg0 = [
            jnp.concatenate([jnp.zeros(n, dtype=jnp.int32),
                             jnp.full((p - n,), -1, dtype=jnp.int32)])
            for n, p in zip(self._sizes, self._pads)]
        self._gh0 = jnp.zeros((1, 4), dtype=jnp.float32)
        self._leaf0 = jnp.zeros(1, dtype=jnp.int32)
        self._root_fn = _root_fn_cached(self.L, self.B,
                                        self._extra_trees, self._has_cat)
        # K pending splits per shard sweep (frontier batching): each
        # staging pass serves up to K splits; 0/1 keeps the legacy
        # one-split-per-sweep loop (also the K-batch's bit-parity
        # reference)
        self._K = max(1, min(
            int(getattr(config, "tpu_frontier_splits", 8)), self.L - 1))
        # cross-ITERATION prefetch scheduling (pipelined boosting): a
        # sweep started but unconsumed when tree t's grow loop ends —
        # or started deliberately at the end of train() — is stashed
        # here, so shard 0 of tree t+1's ROOT sweep stages while the
        # boosting layer runs t's score update and t+1's gradients /
        # gh staging. The stash is always a FRESH (never-iterated)
        # sweep: prestarted sweeps are consumed from the top or not at
        # all, so the ordered-accumulation bit-parity contract is
        # untouched.
        self._next_sweep = None
        self._rebind_compiled()

    def _rebind_compiled(self) -> None:
        """(Re)resolve the lru-cached step programs from the current
        static config (max_depth bakes into finish/kfinish) — called
        at setup and again by ops_refresh.refresh_learner_params after
        a reset_parameter."""
        self._finish_fn = _finish_fn_cached(self.B, self.max_depth,
                                            self._extra_trees,
                                            self._has_cat)
        if self._K > 1:
            self._spec_fn = _spec_fn_cached(self._K)
            self._kfinish_fn = _kfinish_fn_cached(
                self.B, self._K, self.max_depth, self._extra_trees,
                self._has_cat)

    # ------------------------------------------------------------------
    def _check_unsupported(self, config) -> None:
        if self.dataset.bundle is not None:
            log.fatal("sharded datasets never carry EFB bundles")
        if config.linear_tree:
            log.fatal("linear_tree needs raw rows resident; not "
                      "supported with sharded datasets")
        if config.forcedsplits_filename:
            log.fatal("forced splits are not supported with sharded "
                      "datasets")
        if (config.cegb_tradeoff < 1.0 or config.cegb_penalty_split > 0.0
                or config.cegb_penalty_feature_coupled
                or config.cegb_penalty_feature_lazy):
            log.fatal("CEGB is not supported with sharded datasets")
        if config.interaction_constraints \
                or 0.0 < float(config.feature_fraction_bynode) < 1.0:
            log.fatal("per-node feature masks (interaction_constraints "
                      "/ feature_fraction_bynode) are not supported "
                      "with sharded datasets")
        if config.monotone_constraints and any(
                int(v) != 0 for v in config.monotone_constraints) \
                and config.monotone_constraints_method != "basic":
            log.fatal("monotone_constraints_method=%s needs resident "
                      "histogrammed rescans; only 'basic' is supported "
                      "with sharded datasets"
                      % config.monotone_constraints_method)

    def _splittable(self, depth: int) -> bool:
        return self.max_depth <= 0 or depth < self.max_depth

    def _zero_hist(self):
        return _zero_hist_fn_cached(self.Fp, self.B, self._hist_dtype)()

    def _zero_khist(self):
        return _zero_khist_fn_cached(self._K, self.Fp, self.B,
                                     self._hist_dtype)()

    # ------------------------------------------------------------------
    def train(self, grad, hess, bag=None):
        """Grow one tree over the shard sweep; returns the host Tree and
        the device [N] row→leaf vector for the score update — the same
        contract as SerialTreeLearner.train."""
        with obs.scope("tree::stage_gh"):
            ind = self._ones_ind if bag is None else bag
            if self._quantized:
                gh, self._qscale = self._quantize_stage(
                    grad, hess, ind, self._tree_idx + 1)
                gh = _pad_rows_fn_cached(self.R)(gh)
            else:
                self._qscale = self._qs_ones
                gh = _stage_gh_fn_cached(self.R)(grad, hess, ind)
            obs.watch_ready("tree::stage_gh", gh)
            feature_mask = self._sample_features()
        tree = Tree(self.L)
        self._tree_idx += 1
        rand_seed = dev_i32(
            (self._extra_seed + 7919 * self._tree_idx) & 0x7FFFFFFF)
        gh_segs = [
            _gh_seg_fn_cached(n, p)(gh, dev_i32(o))
            for n, p, o in zip(self._sizes, self._pads, self._offsets)]
        leaf_segs = list(self._leaf_seg0)

        if self._K > 1:
            leaf_segs = self._grow_kbatch(tree, gh, gh_segs, leaf_segs,
                                          feature_mask, rand_seed)
        else:
            leaf_segs = self._grow_stepped(tree, gh, gh_segs, leaf_segs,
                                           feature_mask, rand_seed)
        if self._next_sweep is None:
            # schedule the NEXT iteration's root sweep across the
            # boosting boundary: shard 0 stages while the caller runs
            # this tree's score update and the next tree's gradients +
            # gh staging (the last training iteration wastes one
            # worker-side staging — the same accepted cost as the
            # grow loops' early-stop prestarts)
            self._next_sweep = self.prefetcher.sweep()
        rows_out = _rows_out_fn_cached(tuple(self._sizes))
        return tree, rows_out(*leaf_segs)

    # ------------------------------------------------------------------
    def release_prefetch(self) -> None:
        """Drop the cross-iteration sweep stash. Called by the boosting
        layer when a training run ends: the parked sweep pins one
        staged shard buffer in device memory, which is paid-for
        overlap DURING training but dead weight once no further tree
        will consume it. Correctness is unaffected — the next
        ``_root_round`` (continued training) simply starts a fresh
        sweep."""
        self._next_sweep = None

    # ------------------------------------------------------------------
    def _root_round(self, gh, gh_segs, feature_mask, rand_seed):
        """Root round shared by BOTH growth strategies — the lockstep
        matters: the K-batch's bit-parity contract rests on the SAME
        `sharded.root` compile and the same staging/prestart
        discipline as the stepped path. Accumulates the root histogram
        over one staging sweep, scans it, prestarts the first split
        round's sweep through the read-back window, and reads back the
        chosen record (stepped) or the top-K speculation (K-batch).
        Returns (state, recs_dev, recs_host, pending_sweep)."""
        hist = self._zero_hist()
        # the previous iteration stashed this tree's root sweep at its
        # own end (cross-iteration prefetch scheduling; train() above)
        root_sweep, self._next_sweep = (
            self._next_sweep or self.prefetcher.sweep(), None)
        for k, bins_dev in root_sweep:
            hist = _accum_hist_fn(hist, bins_dev, gh_segs[k])
        sums_raw = _sum_gh_fn(gh)
        state, rec = self._root_fn(
            hist, sums_raw, self._gh0, self._leaf0, feature_mask,
            dev_bool(self._splittable(0)), rand_seed, self._qscale,
            self.meta, self.params)
        out = rec if self._K <= 1 else self._spec_fn(state)
        # prestart the first split's sweep: shard 0 stages through
        # the root read-back window instead of after it
        pending = self.prefetcher.sweep() if self.L > 1 else None
        # jaxlint: disable=JLT001 -- the root record(s) must reach the
        # host Tree replay (one deliberate sync per tree root)
        out_h = jax.device_get(out)
        obs.watch_ready("tree::root_histogram", out)
        return state, out, out_h, pending

    # ------------------------------------------------------------------
    def _grow_stepped(self, tree, gh, gh_segs, leaf_segs, feature_mask,
                      rand_seed):
        """Legacy one-split-per-sweep growth (tpu_frontier_splits<=1;
        also the K-batch's bit-parity reference)."""
        with obs.scope("tree::root_histogram"):
            state, rec, rec_h, pending = self._root_round(
                gh, gh_segs, feature_mask, rand_seed)

        next_leaf = 1
        while next_leaf < self.L:
            if not record_is_valid(rec_h):
                break
            small_count = min(float(rec_h.left_total_count),
                              float(rec_h.right_total_count))
            with obs.scope("tree::shard_sweep"):
                hist_small = self._zero_hist()
                new_leaf = dev_i32(next_leaf)
                for k, bins_dev in pending:
                    S = min(max(_next_pow2(int(small_count) + 16),
                                _MIN_BUCKET), self._pads[k])
                    leaf_segs[k], hist_small = _shard_step_fn(
                        bins_dev, leaf_segs[k], gh_segs[k], hist_small,
                        rec, new_leaf, self.meta, S)
            # prestart the NEXT sweep before this split's read-back —
            # the worker overlaps staging with the finish dispatch +
            # sync below (one speculative staging is wasted per tree
            # that stops early; every other split saves a stall)
            pending = (self.prefetcher.sweep()
                       if next_leaf + 1 < self.L else None)
            with obs.scope("tree::split_scan"):
                state, next_rec = self._finish_fn(
                    state, rec, new_leaf, hist_small, feature_mask,
                    rand_seed, self._qscale, self.meta, self.params)
                # jaxlint: disable=JLT001 -- THE per-split host sync:
                # the applied split's record plus the next argmax
                # choice read back together (the sharded analogue of
                # the serial learner's per-batch read-back)
                next_rec_h = jax.device_get(next_rec)
            apply_split_record(tree, self.dataset, rec_h)
            next_leaf += 1
            rec, rec_h = next_rec, next_rec_h
        # a prestarted-but-unconsumed sweep (early stop) is a fresh full
        # sweep — exactly the next iteration's root sweep; stash it
        self._next_sweep = pending
        return leaf_segs

    # ------------------------------------------------------------------
    def _grow_kbatch(self, tree, gh, gh_segs, leaf_segs, feature_mask,
                     rand_seed):
        """K-splits-per-sweep growth (module docstring above the
        k-batch device functions): each round speculates the top-K
        pending candidates, serves all K from ONE staging pass, and
        the validated finish accepts the leaf-wise-order-preserving
        prefix. One host sync per ROUND instead of per split."""
        K = self._K
        with obs.scope("tree::root_histogram"):
            state, spec, spec_h, pending = self._root_round(
                gh, gh_segs, feature_mask, rand_seed)

        next_leaf = 1
        rev_from = np.full(K, -1, dtype=np.int32)
        rev_to = np.zeros(K, dtype=np.int32)
        while next_leaf < self.L:
            slots = [_slot(spec_h, i) for i in range(K)]
            n_slots = min(K, self.L - next_leaf)
            # speculation validity is a prefix: slots come gain-sorted
            n_valid = 0
            while n_valid < n_slots and record_is_valid(slots[n_valid]):
                n_valid += 1
            if n_valid == 0:
                break
            small_max = max(
                min(float(slots[i].left_total_count),
                    float(slots[i].right_total_count))
                for i in range(n_valid))
            # explicit device staging of the round's control vectors
            # (transfer-guard discipline: one deliberate device_put
            # per round, never an implicit transfer)
            sv_dev = jax.device_put(
                np.arange(K, dtype=np.int32) < n_valid)
            rf_dev = jax.device_put(rev_from)
            rt_dev = jax.device_put(rev_to)
            nlb = dev_i32(next_leaf)
            if pending is None:
                # the previous round's rejections forced an extra
                # round the prestart heuristic did not cover
                pending = self.prefetcher.sweep()
            with obs.scope("tree::shard_sweep"):
                hists = self._zero_khist()
                for k, bins_dev in pending:
                    S = min(max(_next_pow2(int(small_max) + 16),
                                _MIN_BUCKET), self._pads[k])
                    leaf_segs[k], hists = _shard_kstep_fn(
                        bins_dev, leaf_segs[k], gh_segs[k], hists,
                        spec, nlb, sv_dev, rf_dev, rt_dev, self.meta,
                        K, S)
            # prestart the next round's staging only when even a fully
            # accepted round leaves splits to grow (a rejected tail
            # instead pays one stall at the loop top)
            pending = (self.prefetcher.sweep()
                       if next_leaf + n_valid < self.L else None)
            with obs.scope("tree::split_scan"):
                state, accepted = self._kfinish_fn(
                    state, spec, hists, nlb, sv_dev, feature_mask,
                    rand_seed, self._qscale, self.meta, self.params)
                spec = self._spec_fn(state)
                # jaxlint: disable=JLT001 -- THE per-round host sync:
                # the accepted mask plus the next round's speculation
                # read back in one hop (the K-batch analogue of the
                # stepped path's per-split read-back)
                accepted_h, spec_h = jax.device_get((accepted, spec))
            n_acc = 0
            while n_acc < K and bool(accepted_h[n_acc]):
                n_acc += 1
            for i in range(n_acc):
                apply_split_record(tree, self.dataset, slots[i])
            rev_from = np.full(K, -1, dtype=np.int32)
            rev_to = np.zeros(K, dtype=np.int32)
            for i in range(n_acc, n_valid):
                rev_from[i] = next_leaf + i
                rev_to[i] = int(slots[i].leaf)
            next_leaf += n_acc
            if n_acc == 0:
                break  # defensive: slot 0 is argmax-pinned

        if (rev_from >= 0).any():
            # the loop ended with rejected routings still applied:
            # revert them before the partition feeds the score update
            # (no further sweep folds the revert in)
            rf_dev = jax.device_put(rev_from)
            rt_dev = jax.device_put(rev_to)
            rev = _revert_fn_cached(K)
            for k in range(len(leaf_segs)):
                leaf_segs[k] = rev(leaf_segs[k], rf_dev, rt_dev)
        # stash a prestarted-but-unconsumed sweep for the next
        # iteration's root (same as the stepped path)
        self._next_sweep = pending
        return leaf_segs


_accum_hist_fn = obs_compile.instrument_jit("sharded.accum_hist",
                                            _accum_hist)
