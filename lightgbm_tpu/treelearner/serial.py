"""Single-chip leaf-wise tree learner.

TPU-native counterpart of the reference's SerialTreeLearner
(src/treelearner/serial_tree_learner.cpp:159 ``Train``) and, closer in
spirit, its CUDA whole-loop learner
(src/treelearner/cuda/cuda_single_gpu_tree_learner.cpp:128): all heavy state
— binned rows, gradients, per-leaf histograms, the row→leaf partition — is
device-resident; the host only orchestrates the leaf loop and records the
chosen splits into the host ``Tree``.

XLA needs static shapes, so the two data-dependent quantities are handled as:

- **row→leaf partition**: a full-length ``leaf_of_row`` vector updated by a
  vectorized compare on the split feature's bin column (no index lists; the
  analogue of the reference's DataPartition::Split,
  src/treelearner/data_partition.hpp:21 / cuda_data_partition.cu:288).
- **per-leaf row gather**: rows of the leaf to histogram are compacted with
  ``jnp.nonzero(..., size=S)`` where the static size S is the smaller-child
  count rounded up to a power of two; one jitted step function is compiled
  per bucket size (~log2(N) variants, cached). Padding rows point at a
  dummy row whose (grad, hess, count) are zero so they vanish from sums.

Per split step (one device dispatch, one small host readback):
  apply pending split -> partition update -> gather smaller child ->
  histogram it -> sibling by subtraction (serial_tree_learner.cpp:421) ->
  best-split scan for both children -> argmax over all leaf candidates ->
  return the next winning split record to the host.

The host loop mirrors the reference's ``Train`` loop: split the best leaf,
stop when num_leaves is reached or no candidate has positive gain.
max_depth gating follows BeforeFindBestSplit (serial_tree_learner.cpp:287):
a leaf at depth d is splittable iff max_depth <= 0 or d < max_depth —
enforced by zeroing candidate gains at record-creation time.
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..io.binning import MissingType
from ..io.dataset import BinnedDataset
from ..models.tree import Tree
from ..ops.histogram import build_histogram, subtract_histogram
from ..ops.split import (FeatureMeta, SplitInfo, SplitParams, find_best_split)
from ..utils import log

_NEG_INF = -jnp.inf
_MIN_BUCKET = 256


def _next_pow2(n: int) -> int:
    n = max(int(n), 1)
    return 1 << (n - 1).bit_length()


class GrowState(NamedTuple):
    """Device-resident per-tree state (the analogue of the CUDA learner's
    CUDALeafSplits + histogram + partition buffers)."""
    leaf_of_row: jnp.ndarray      # [R] i32 (R = N+1; last row is a dummy, -1)
    gh: jnp.ndarray               # [R, 4] f32 (grad, hess, in-bag, total=1)
    hists: jnp.ndarray            # [L, F, B, 4] f32
    # Per-leaf best-split candidates (SplitInfo fields, array-of-struct):
    gain: jnp.ndarray             # [L] f32, -inf when invalid
    feature: jnp.ndarray          # [L] i32
    threshold_bin: jnp.ndarray    # [L] i32
    default_left: jnp.ndarray     # [L] bool
    is_categorical: jnp.ndarray   # [L] bool
    cat_mask: jnp.ndarray         # [L, B] bool — bins going left (cat)
    # monotone bounds each candidate's children would inherit
    cand_left_min: jnp.ndarray    # [L] f32
    cand_left_max: jnp.ndarray
    cand_right_min: jnp.ndarray
    cand_right_max: jnp.ndarray
    left_sum_grad: jnp.ndarray    # [L] f32
    left_sum_hess: jnp.ndarray
    left_count: jnp.ndarray
    left_total_count: jnp.ndarray
    left_output: jnp.ndarray
    right_sum_grad: jnp.ndarray
    right_sum_hess: jnp.ndarray
    right_count: jnp.ndarray
    right_total_count: jnp.ndarray
    right_output: jnp.ndarray


class SplitRecord(NamedTuple):
    """One winning split, read back to the host each step."""
    leaf: jnp.ndarray
    gain: jnp.ndarray
    feature: jnp.ndarray
    threshold_bin: jnp.ndarray
    default_left: jnp.ndarray
    is_categorical: jnp.ndarray
    cat_mask: jnp.ndarray
    left_sum_grad: jnp.ndarray
    left_sum_hess: jnp.ndarray
    left_count: jnp.ndarray
    left_total_count: jnp.ndarray
    left_output: jnp.ndarray
    right_sum_grad: jnp.ndarray
    right_sum_hess: jnp.ndarray
    right_count: jnp.ndarray
    right_total_count: jnp.ndarray
    right_output: jnp.ndarray


def _record_at(state: GrowState, leaf) -> SplitRecord:
    return SplitRecord(
        leaf=leaf, gain=state.gain[leaf], feature=state.feature[leaf],
        threshold_bin=state.threshold_bin[leaf],
        default_left=state.default_left[leaf],
        is_categorical=state.is_categorical[leaf],
        cat_mask=state.cat_mask[leaf],
        left_sum_grad=state.left_sum_grad[leaf],
        left_sum_hess=state.left_sum_hess[leaf],
        left_count=state.left_count[leaf],
        left_total_count=state.left_total_count[leaf],
        left_output=state.left_output[leaf],
        right_sum_grad=state.right_sum_grad[leaf],
        right_sum_hess=state.right_sum_hess[leaf],
        right_count=state.right_count[leaf],
        right_total_count=state.right_total_count[leaf],
        right_output=state.right_output[leaf])


def _store_info(state: GrowState, leaf, info: SplitInfo,
                allowed) -> GrowState:
    return state._replace(
        gain=state.gain.at[leaf].set(jnp.where(allowed, info.gain, _NEG_INF)),
        feature=state.feature.at[leaf].set(info.feature),
        threshold_bin=state.threshold_bin.at[leaf].set(info.threshold_bin),
        default_left=state.default_left.at[leaf].set(info.default_left),
        is_categorical=state.is_categorical.at[leaf].set(
            info.is_categorical),
        cat_mask=state.cat_mask.at[leaf].set(info.cat_mask),
        cand_left_min=state.cand_left_min.at[leaf].set(
            info.left_min_output),
        cand_left_max=state.cand_left_max.at[leaf].set(
            info.left_max_output),
        cand_right_min=state.cand_right_min.at[leaf].set(
            info.right_min_output),
        cand_right_max=state.cand_right_max.at[leaf].set(
            info.right_max_output),
        left_sum_grad=state.left_sum_grad.at[leaf].set(info.left_sum_grad),
        left_sum_hess=state.left_sum_hess.at[leaf].set(info.left_sum_hess),
        left_count=state.left_count.at[leaf].set(info.left_count),
        left_total_count=state.left_total_count.at[leaf].set(
            info.left_total_count),
        left_output=state.left_output.at[leaf].set(info.left_output),
        right_sum_grad=state.right_sum_grad.at[leaf].set(info.right_sum_grad),
        right_sum_hess=state.right_sum_hess.at[leaf].set(info.right_sum_hess),
        right_count=state.right_count.at[leaf].set(info.right_count),
        right_total_count=state.right_total_count.at[leaf].set(
            info.right_total_count),
        right_output=state.right_output.at[leaf].set(info.right_output))


def _go_left_by_bin(col: jnp.ndarray, tbin, default_left,
                    missing_type, nan_bin, zero_bin,
                    is_categorical=None, cat_mask=None) -> jnp.ndarray:
    """Training-time split direction over bin values (reference:
    DenseBin::Split templated missing handling, src/io/dense_bin.hpp;
    categorical bitset routing ≙ DenseBin::SplitCategorical)."""
    gl = col <= tbin
    gl = jnp.where((missing_type == MissingType.NAN) & (col == nan_bin),
                   default_left, gl)
    gl = jnp.where((missing_type == MissingType.ZERO) & (col == zero_bin),
                   default_left, gl)
    if is_categorical is not None:
        gl = jnp.where(is_categorical, cat_mask[col], gl)
    return gl


class SerialTreeLearner:
    """Leaf-wise grower over a device-resident binned dataset."""

    def __init__(self, config, dataset: BinnedDataset):
        self.config = config
        self.dataset = dataset
        N, F = dataset.bins.shape
        if F == 0:
            log.fatal("Cannot train without features")
        self.N, self.F = N, F
        self.B = max(int(dataset.max_num_bin), 2)
        self.L = int(config.num_leaves)
        self.max_depth = int(config.max_depth)
        # dummy row N: bins 0, gh 0, leaf -1
        pad = np.zeros((1, F), dtype=dataset.bins.dtype)
        self.bins = jnp.asarray(np.concatenate([dataset.bins, pad], axis=0))
        self.meta = FeatureMeta.from_dataset(
            dataset, int(config.max_cat_to_onehot))
        self.params = SplitParams.from_config(config)
        self._ff_rng = np.random.RandomState(config.feature_fraction_seed)
        self._resolve_constraints()
        self._step_cache = {}
        self._root_fn = jax.jit(self._root_impl)
        self._max_bucket = _next_pow2(N)

    # ------------------------------------------------------------------
    def _sample_features(self) -> jnp.ndarray:
        """Per-tree column sampling (reference: ColSampler,
        src/treelearner/col_sampler.hpp:20)."""
        ff = float(self.config.feature_fraction)
        mask = np.ones(self.F, dtype=bool)
        if 0.0 < ff < 1.0:
            k = max(1, int(round(self.F * ff)))
            mask[:] = False
            mask[self._ff_rng.choice(self.F, k, replace=False)] = True
        if self._constraint_groups is not None:
            allowed = np.zeros(self.F, dtype=bool)
            for grp in self._constraint_groups:
                allowed[list(grp)] = True
            mask &= allowed
        return jnp.asarray(mask)

    def _resolve_constraints(self):
        """interaction_constraints (config.h:562): groups of inner feature
        indices; a branch may only combine features co-occurring in at
        least one group (reference: ColSampler::SetUsedFeatureByNode)."""
        ic = self.config.interaction_constraints
        if not ic:
            self._constraint_groups = None
            return
        groups = []
        for grp in ic:
            inner = set()
            for real_f in grp:
                j = self.dataset.inner_feature_index(int(real_f))
                if j >= 0:
                    inner.add(j)
            if inner:
                groups.append(frozenset(inner))
        self._constraint_groups = groups or None

    def _node_mask(self, tree_mask: jnp.ndarray,
                   path_features: frozenset) -> jnp.ndarray:
        """Per-node mask: interaction constraints filtered by the
        feature-path, plus feature_fraction_bynode sampling."""
        mask = None
        if self._constraint_groups is not None:
            allowed = np.zeros(self.F, dtype=bool)
            for grp in self._constraint_groups:
                if path_features <= grp:
                    allowed[list(grp)] = True
            mask = allowed
        ffb = float(self.config.feature_fraction_bynode)
        if 0.0 < ffb < 1.0:
            m2 = np.zeros(self.F, dtype=bool)
            k = max(1, int(round(self.F * ffb)))
            m2[self._ff_rng.choice(self.F, k, replace=False)] = True
            mask = m2 if mask is None else (mask & m2)
        if mask is None:
            return tree_mask
        return tree_mask & jnp.asarray(mask)

    # ------------------------------------------------------------------
    def _root_impl(self, gh: jnp.ndarray, feature_mask: jnp.ndarray,
                   children_allowed) -> Tuple[GrowState, SplitRecord]:
        hist = build_histogram(self.bins, gh, self.B)
        sums = jnp.sum(gh, axis=0)
        info = find_best_split(hist, sums[0], sums[1], sums[2], sums[3],
                               self.meta, self.params, feature_mask)
        L, F, B = self.L, self.F, self.B
        leaf_of_row = jnp.concatenate([
            jnp.zeros(self.N, dtype=jnp.int32),
            jnp.full((1,), -1, dtype=jnp.int32)])
        zf = lambda: jnp.zeros(L, dtype=jnp.float32)
        state = GrowState(
            leaf_of_row=leaf_of_row, gh=gh,
            hists=jnp.zeros((L, F, B, 4), dtype=jnp.float32).at[0].set(hist),
            gain=jnp.full(L, _NEG_INF, dtype=jnp.float32),
            feature=jnp.full(L, -1, dtype=jnp.int32),
            threshold_bin=jnp.zeros(L, dtype=jnp.int32),
            default_left=jnp.zeros(L, dtype=bool),
            is_categorical=jnp.zeros(L, dtype=bool),
            cat_mask=jnp.zeros((L, B), dtype=bool),
            cand_left_min=jnp.full(L, -jnp.inf, dtype=jnp.float32),
            cand_left_max=jnp.full(L, jnp.inf, dtype=jnp.float32),
            cand_right_min=jnp.full(L, -jnp.inf, dtype=jnp.float32),
            cand_right_max=jnp.full(L, jnp.inf, dtype=jnp.float32),
            left_sum_grad=zf(), left_sum_hess=zf(), left_count=zf(),
            left_total_count=zf(), left_output=zf(), right_sum_grad=zf(),
            right_sum_hess=zf(), right_count=zf(), right_total_count=zf(),
            right_output=zf())
        state = _store_info(state, 0, info, children_allowed)
        return state, _record_at(state, 0)

    # ------------------------------------------------------------------
    def _make_step(self, S: int):
        meta, params, B = self.meta, self.params, self.B
        bins = self.bins
        R = self.N + 1

        def step(state: GrowState, leaf, new_leaf, children_allowed,
                 mask_left, mask_right):
            f = state.feature[leaf]
            tbin = state.threshold_bin[leaf]
            dl = state.default_left[leaf]
            col = jnp.take(bins, f, axis=1).astype(jnp.int32)
            gl = _go_left_by_bin(col, tbin, dl, meta.missing_type[f],
                                 meta.num_bin[f] - 1, meta.zero_bin[f],
                                 state.is_categorical[leaf],
                                 state.cat_mask[leaf])
            on_leaf = state.leaf_of_row == leaf
            leaf_of_row = jnp.where(on_leaf & ~gl, new_leaf,
                                    state.leaf_of_row)

            lc, rc = state.left_count[leaf], state.right_count[leaf]
            ltc, rtc = (state.left_total_count[leaf],
                        state.right_total_count[leaf])
            smaller_is_left = ltc <= rtc
            small_id = jnp.where(smaller_is_left, leaf, new_leaf)
            (idx,) = jnp.nonzero(leaf_of_row == small_id, size=S,
                                 fill_value=R - 1)
            hist_small = build_histogram(bins[idx], state.gh[idx], B)
            hist_large = subtract_histogram(state.hists[leaf], hist_small)
            hist_left = jnp.where(smaller_is_left, hist_small, hist_large)
            hist_right = jnp.where(smaller_is_left, hist_large, hist_small)
            hists = state.hists.at[leaf].set(hist_left) \
                               .at[new_leaf].set(hist_right)

            left_info = find_best_split(
                hist_left, state.left_sum_grad[leaf],
                state.left_sum_hess[leaf], lc, ltc, meta, params,
                mask_left, state.cand_left_min[leaf],
                state.cand_left_max[leaf])
            right_info = find_best_split(
                hist_right, state.right_sum_grad[leaf],
                state.right_sum_hess[leaf], rc, rtc, meta, params,
                mask_right, state.cand_right_min[leaf],
                state.cand_right_max[leaf])

            state = state._replace(leaf_of_row=leaf_of_row, hists=hists)
            state = _store_info(state, leaf, left_info, children_allowed)
            state = _store_info(state, new_leaf, right_info, children_allowed)
            best = jnp.argmax(state.gain).astype(jnp.int32)
            return state, _record_at(state, best)

        return jax.jit(step, donate_argnums=(0,))

    def _step_fn(self, S: int):
        if S not in self._step_cache:
            self._step_cache[S] = self._make_step(S)
        return self._step_cache[S]

    def _bucket(self, count: float) -> int:
        # +1 margin: counts travel as f32 sums and may round down for very
        # large leaves. The floor caps the number of compiled step variants
        # at ~log2(N) - 8.
        return min(max(_next_pow2(int(count) + 1), _MIN_BUCKET),
                   self._max_bucket)

    # ------------------------------------------------------------------
    def _splittable(self, depth: int) -> bool:
        return self.max_depth <= 0 or depth < self.max_depth

    def train(self, grad: jnp.ndarray, hess: jnp.ndarray,
              bag: Optional[jnp.ndarray] = None
              ) -> Tuple[Tree, jnp.ndarray]:
        """Grow one tree. ``grad``/``hess`` are f32[N] device arrays;
        ``bag`` an optional f32[N] in-bag indicator (0/1). Returns the host
        Tree and the final [N] row→leaf assignment (device) for score
        updates (reference: GBDT::UpdateScore uses the learner's partition,
        src/boosting/gbdt.cpp:475)."""
        ind = jnp.ones(self.N, dtype=jnp.float32) if bag is None else bag
        gh = jnp.stack([grad * ind, hess * ind, ind,
                        jnp.ones(self.N, dtype=jnp.float32)], axis=1)
        gh = jnp.concatenate(
            [gh, jnp.zeros((1, 4), dtype=jnp.float32)], axis=0)
        feature_mask = self._sample_features()

        tree = Tree(self.L)
        state, rec = self._root_fn(gh, feature_mask, self._splittable(0))
        pending = jax.device_get(rec)
        # per-leaf feature path (for interaction constraints / bynode)
        paths = {0: frozenset()}
        per_node = (self._constraint_groups is not None
                    or 0.0 < float(self.config.feature_fraction_bynode)
                    < 1.0)
        for k in range(1, self.L):
            leaf = int(pending.leaf)
            if int(pending.feature) < 0 or not np.isfinite(float(pending.gain)) \
                    or float(pending.gain) <= 0.0:
                break
            f = int(pending.feature)
            tbin = int(pending.threshold_bin)
            mapper = self.dataset.bin_mappers[f]
            common = dict(
                leaf=leaf, feature=self.dataset.real_feature_index(f),
                feature_inner=f,
                left_value=float(pending.left_output),
                right_value=float(pending.right_output),
                left_count=int(round(float(pending.left_count))),
                right_count=int(round(float(pending.right_count))),
                left_weight=float(pending.left_sum_hess),
                right_weight=float(pending.right_sum_hess),
                gain=float(pending.gain))
            if bool(pending.is_categorical):
                bin_mask = np.asarray(pending.cat_mask)
                cats = [mapper.bin_2_categorical[b]
                        for b in np.nonzero(bin_mask)[0]
                        if b < len(mapper.bin_2_categorical)]
                tree.split_categorical(
                    cat_values=cats, bin_mask=bin_mask, **common)
            else:
                tree.split(
                    threshold_bin=tbin,
                    threshold_real=self.dataset.real_threshold(f, tbin),
                    missing_type=mapper.missing_type,
                    default_left=bool(pending.default_left), **common)
            children_allowed = self._splittable(int(tree.leaf_depth[leaf]))
            smaller = min(float(pending.left_total_count),
                          float(pending.right_total_count))
            S = self._bucket(smaller)
            paths[leaf] = paths[k] = paths.get(leaf, frozenset()) | {f}
            if per_node:
                mask_left = self._node_mask(feature_mask, paths[leaf])
                mask_right = self._node_mask(feature_mask, paths[k])
            else:
                mask_left = mask_right = feature_mask
            state, rec = self._step_fn(S)(
                state, jnp.int32(leaf), jnp.int32(k),
                jnp.asarray(children_allowed), mask_left, mask_right)
            pending = jax.device_get(rec)
        return tree, state.leaf_of_row[:self.N]
