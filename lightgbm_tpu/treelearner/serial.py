"""Single-chip leaf-wise tree learner.

TPU-native counterpart of the reference's SerialTreeLearner
(src/treelearner/serial_tree_learner.cpp:159 ``Train``) and, closer in
spirit, its CUDA whole-loop learner
(src/treelearner/cuda/cuda_single_gpu_tree_learner.cpp:128): all heavy state
— binned rows, gradients, per-leaf histograms, the row→leaf partition — is
device-resident; the host only orchestrates batches of split steps and
records the chosen splits into the host ``Tree``.

The binned matrix is a **traced argument** of every jitted function, never a
closed-over constant: closing over it would embed the whole dataset into the
HLO as a literal, making the compiled program scale with the data (at Higgs
scale ~300 MB of program).

XLA needs static shapes, so the two data-dependent quantities are handled as:

- **row→leaf partition**: a full-length ``leaf_of_row`` vector updated by a
  vectorized compare on the split feature's bin column (no index lists; the
  analogue of the reference's DataPartition::Split,
  src/treelearner/data_partition.hpp:21 / cuda_data_partition.cu:288).
- **per-leaf row gather**: rows of the leaf to histogram are compacted with
  ``jnp.nonzero(..., size=S)`` where the static size S is a power of two
  ≥ half the largest current leaf. Padding rows point at a dummy row whose
  (grad, hess, count) are zero so they vanish from sums.

Unlike the reference's CUDA learner (one host sync per split), split steps
run in **batches**: a ``lax.fori_loop`` executes k split steps per device
dispatch — the device itself argmaxes the next leaf to split, applies the
split, histograms the smaller child, scans both children — and a buffer of
k split records is read back per batch. S stays valid for a whole batch
because the maximum leaf size never grows as splits proceed; k is derived
from S (many steps per dispatch once gathers are small) so both the number
of host round-trips per tree (~log₂ num_leaves + num_leaves/32) and the
number of compiled variants (~log₂ N, keyed on S alone) stay small.

max_depth gating follows BeforeFindBestSplit (serial_tree_learner.cpp:287):
a leaf at depth d is splittable iff max_depth <= 0 or d < max_depth —
enforced on device by zeroing candidate gains at record-creation time,
using a device-resident per-leaf depth vector.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..io.binning import MissingType
from ..io.dataset import BinnedDataset
from ..models.tree import Tree
from ..obs import compile as obs_compile
from ..obs.registry import registry as obs
from ..ops.histogram import (build_histogram, subtract_histogram,
                             unpack_bundle_histogram)
from ..ops.quantize import dequantize_sums, sum_gh
from ..ops.split import (FeatureMeta, SplitInfo, SplitParams,
                         calculate_leaf_output, find_best_split,
                         make_rand_bins)
from ..utils import log, next_pow2 as _next_pow2
from ..utils.scalars import dev_bool, dev_i32
from .capabilities import (CapabilityMixin, train_cegb, train_monotone,
                           train_stepwise)

_NEG_INF = -jnp.inf
_MIN_BUCKET = 256
# Splits per device dispatch cap. Each batch costs one host round-trip
# (~27 ms through the TPU tunnel, measured round 3); with the Pallas
# histogram kernel a split step is ≲1 ms at typical gather sizes, so
# larger batches trade a little wasted compute (stale gather size S) for
# far fewer syncs: ~12 dispatches/tree at 255 leaves.
_MAX_BATCH = 64


class GrowState(NamedTuple):
    """Device-resident per-tree state (the analogue of the CUDA learner's
    CUDALeafSplits + histogram + partition buffers)."""
    leaf_of_row: jnp.ndarray      # [R] i32 (R = N+1; last row is a dummy, -1)
    gh: jnp.ndarray               # [R, 4] f32 (grad, hess, in-bag, total=1)
    hists: jnp.ndarray            # [L, F, B, 4] f32
    leaf_depth: jnp.ndarray       # [L] i32 — device-side max_depth gating
    # Per-leaf best-split candidates (SplitInfo fields, array-of-struct):
    gain: jnp.ndarray             # [L] f32, -inf when invalid
    feature: jnp.ndarray          # [L] i32
    threshold_bin: jnp.ndarray    # [L] i32
    default_left: jnp.ndarray    # [L] bool
    is_categorical: jnp.ndarray   # [L] bool
    cat_mask: jnp.ndarray         # [L, B] bool — bins going left (cat)
    # monotone bounds each candidate's children would inherit
    cand_left_min: jnp.ndarray    # [L] f32
    cand_left_max: jnp.ndarray
    cand_right_min: jnp.ndarray
    cand_right_max: jnp.ndarray
    left_sum_grad: jnp.ndarray    # [L] f32
    left_sum_hess: jnp.ndarray
    left_count: jnp.ndarray
    left_total_count: jnp.ndarray
    left_output: jnp.ndarray
    right_sum_grad: jnp.ndarray
    right_sum_hess: jnp.ndarray
    right_count: jnp.ndarray
    right_total_count: jnp.ndarray
    right_output: jnp.ndarray


class SplitRecord(NamedTuple):
    """One winning split, read back to the host (per step or per batch)."""
    leaf: jnp.ndarray
    gain: jnp.ndarray
    feature: jnp.ndarray
    threshold_bin: jnp.ndarray
    default_left: jnp.ndarray
    is_categorical: jnp.ndarray
    cat_mask: jnp.ndarray
    left_sum_grad: jnp.ndarray
    left_sum_hess: jnp.ndarray
    left_count: jnp.ndarray
    left_total_count: jnp.ndarray
    left_output: jnp.ndarray
    right_sum_grad: jnp.ndarray
    right_sum_hess: jnp.ndarray
    right_count: jnp.ndarray
    right_total_count: jnp.ndarray
    right_output: jnp.ndarray


def _record_at(state: GrowState, leaf) -> SplitRecord:
    return SplitRecord(
        leaf=leaf, gain=state.gain[leaf], feature=state.feature[leaf],
        threshold_bin=state.threshold_bin[leaf],
        default_left=state.default_left[leaf],
        is_categorical=state.is_categorical[leaf],
        cat_mask=state.cat_mask[leaf],
        left_sum_grad=state.left_sum_grad[leaf],
        left_sum_hess=state.left_sum_hess[leaf],
        left_count=state.left_count[leaf],
        left_total_count=state.left_total_count[leaf],
        left_output=state.left_output[leaf],
        right_sum_grad=state.right_sum_grad[leaf],
        right_sum_hess=state.right_sum_hess[leaf],
        right_count=state.right_count[leaf],
        right_total_count=state.right_total_count[leaf],
        right_output=state.right_output[leaf])


def _empty_records(k: int, B: int) -> SplitRecord:
    """[k]-shaped record buffers; feature = -1 marks never-written slots."""
    zi = jnp.zeros(k, dtype=jnp.int32)
    zf = jnp.zeros(k, dtype=jnp.float32)
    zb = jnp.zeros(k, dtype=bool)
    return SplitRecord(
        leaf=zi, gain=jnp.full(k, _NEG_INF, dtype=jnp.float32),
        feature=jnp.full(k, -1, dtype=jnp.int32), threshold_bin=zi,
        default_left=zb, is_categorical=zb,
        cat_mask=jnp.zeros((k, B), dtype=bool),
        left_sum_grad=zf, left_sum_hess=zf, left_count=zf,
        left_total_count=zf, left_output=zf,
        right_sum_grad=zf, right_sum_hess=zf, right_count=zf,
        right_total_count=zf, right_output=zf)


def _store_info(state: GrowState, leaf, info: SplitInfo, allowed,
                valid=True) -> GrowState:
    """Write a leaf's candidate split; ``allowed`` zeroes the gain
    (max_depth gating), ``valid`` guards the whole write (batched steps
    after the no-more-splits point must leave state untouched)."""
    def put(arr, new):
        return arr.at[leaf].set(jnp.where(valid, new, arr[leaf]))
    return state._replace(
        gain=put(state.gain, jnp.where(allowed, info.gain, _NEG_INF)),
        feature=put(state.feature, info.feature),
        threshold_bin=put(state.threshold_bin, info.threshold_bin),
        default_left=put(state.default_left, info.default_left),
        is_categorical=put(state.is_categorical, info.is_categorical),
        cat_mask=state.cat_mask.at[leaf].set(
            jnp.where(valid, info.cat_mask, state.cat_mask[leaf])),
        cand_left_min=put(state.cand_left_min, info.left_min_output),
        cand_left_max=put(state.cand_left_max, info.left_max_output),
        cand_right_min=put(state.cand_right_min, info.right_min_output),
        cand_right_max=put(state.cand_right_max, info.right_max_output),
        left_sum_grad=put(state.left_sum_grad, info.left_sum_grad),
        left_sum_hess=put(state.left_sum_hess, info.left_sum_hess),
        left_count=put(state.left_count, info.left_count),
        left_total_count=put(state.left_total_count, info.left_total_count),
        left_output=put(state.left_output, info.left_output),
        right_sum_grad=put(state.right_sum_grad, info.right_sum_grad),
        right_sum_hess=put(state.right_sum_hess, info.right_sum_hess),
        right_count=put(state.right_count, info.right_count),
        right_total_count=put(state.right_total_count,
                              info.right_total_count),
        right_output=put(state.right_output, info.right_output))


def make_root_state(gh, hist, leaf_of_row, info, L: int, F: int, B: int,
                    children_allowed, hist_slots: int = 0) -> GrowState:
    """Initial GrowState after the root histogram+scan (shared by the
    serial and mesh-parallel learners). ``hist_slots`` shrinks the
    per-leaf histogram store for learners that never re-read it (the
    voting learner re-votes per leaf instead of subtracting)."""
    hist_slots = hist_slots or L
    zf = lambda: jnp.zeros(L, dtype=jnp.float32)
    state = GrowState(
        leaf_of_row=leaf_of_row, gh=gh,
        hists=jnp.zeros((hist_slots, F, B, 4),
                        dtype=hist.dtype).at[0].set(hist),
        leaf_depth=jnp.zeros(L, dtype=jnp.int32),
        gain=jnp.full(L, _NEG_INF, dtype=jnp.float32),
        feature=jnp.full(L, -1, dtype=jnp.int32),
        threshold_bin=jnp.zeros(L, dtype=jnp.int32),
        default_left=jnp.zeros(L, dtype=bool),
        is_categorical=jnp.zeros(L, dtype=bool),
        cat_mask=jnp.zeros((L, B), dtype=bool),
        cand_left_min=jnp.full(L, -jnp.inf, dtype=jnp.float32),
        cand_left_max=jnp.full(L, jnp.inf, dtype=jnp.float32),
        cand_right_min=jnp.full(L, -jnp.inf, dtype=jnp.float32),
        cand_right_max=jnp.full(L, jnp.inf, dtype=jnp.float32),
        left_sum_grad=zf(), left_sum_hess=zf(), left_count=zf(),
        left_total_count=zf(), left_output=zf(), right_sum_grad=zf(),
        right_sum_hess=zf(), right_count=zf(), right_total_count=zf(),
        right_output=zf())
    return _store_info(state, 0, info, children_allowed)


def record_is_valid(rec) -> bool:
    """Host-side check of a read-back split record."""
    return (int(rec.feature) >= 0 and np.isfinite(float(rec.gain))
            and float(rec.gain) > 0.0)


def rec_valid(rec: SplitRecord):
    """Device-side twin of record_is_valid — the two predicates MUST stay
    in lockstep (the device suppresses state writes for invalid records,
    the host stops applying them; divergence would desync the tree from
    the partition)."""
    return ((rec.feature >= 0) & jnp.isfinite(rec.gain)
            & (rec.gain > 0.0))


def apply_split_record(tree: Tree, dataset: BinnedDataset, rec) -> None:
    """Replay one device split record into the host Tree (reference:
    the Tree::Split call inside SerialTreeLearner::Split,
    serial_tree_learner.cpp:593)."""
    leaf = int(rec.leaf)
    f = int(rec.feature)
    tbin = int(rec.threshold_bin)
    mapper = dataset.bin_mappers[f]
    common = dict(
        leaf=leaf, feature=dataset.real_feature_index(f),
        feature_inner=f,
        left_value=float(rec.left_output),
        right_value=float(rec.right_output),
        left_count=int(round(float(rec.left_count))),
        right_count=int(round(float(rec.right_count))),
        left_weight=float(rec.left_sum_hess),
        right_weight=float(rec.right_sum_hess),
        gain=float(rec.gain))
    if bool(rec.is_categorical):
        bin_mask = np.asarray(rec.cat_mask)
        cats = [mapper.bin_2_categorical[b]
                for b in np.nonzero(bin_mask)[0]
                if b < len(mapper.bin_2_categorical)]
        tree.split_categorical(cat_values=cats, bin_mask=bin_mask, **common)
    else:
        tree.split(
            threshold_bin=tbin,
            threshold_real=dataset.real_threshold(f, tbin),
            missing_type=mapper.missing_type,
            default_left=bool(rec.default_left), **common)


def _go_left_by_bin(col: jnp.ndarray, tbin, default_left,
                    missing_type, nan_bin, zero_bin,
                    is_categorical=None, cat_mask=None) -> jnp.ndarray:
    """Training-time split direction over bin values (reference:
    DenseBin::Split templated missing handling, src/io/dense_bin.hpp;
    categorical bitset routing ≙ DenseBin::SplitCategorical)."""
    gl = col <= tbin
    gl = jnp.where((missing_type == MissingType.NAN) & (col == nan_bin),
                   default_left, gl)
    gl = jnp.where((missing_type == MissingType.ZERO) & (col == zero_bin),
                   default_left, gl)
    if is_categorical is not None:
        gl = jnp.where(is_categorical, cat_mask[col], gl)
    return gl


# ----------------------------------------------------------------------
# Jitted step functions. Module-level + lru_cache so the compiled
# executables are shared across learner instances (every test / Booster
# builds a new learner; per-instance closures would recompile the same
# graphs). All data — bins, meta, params — is traced arguments; only
# shapes and structural flags are static.
# ----------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _stage_gh_fn_cached(R: int):
    """One fused dispatch staging (grad, hess, ind) → padded [R, 4] gh.
    The former eager jnp.ones/stack/concatenate chain launched ~5 tiny
    dispatches per tree and performed implicit scalar transfers (each
    fill constant became a device buffer per call) — the transfer-guard
    sanitizer test pins this staging transfer-free."""
    def stage(grad, hess, ind):
        n = grad.shape[0]
        gh = jnp.stack([grad * ind, hess * ind, ind,
                        jnp.ones_like(ind)], axis=1)
        return jnp.concatenate(
            [gh, jnp.zeros((R - n, 4), dtype=gh.dtype)], axis=0)

    return obs_compile.instrument_jit("serial.stage_gh", stage)


@functools.lru_cache(maxsize=None)
def _rows_out_fn_cached(N: int):
    """[R] → [N] unpadded row view, jitted: an eager ``[:N]`` slice
    turns its bounds into device scalars per call (implicit
    transfers)."""
    def rows_out(leaf_of_row):
        return leaf_of_row[:N]

    return obs_compile.instrument_jit("serial.rows_out", rows_out)


@functools.lru_cache(maxsize=None)
def _pad_rows_fn_cached(R: int):
    """Pad quantized [N, 4] gh rows to the learner's padded row count
    (zero rows vanish from every histogram sum)."""
    def pad(gh):
        n = gh.shape[0]
        return jnp.concatenate(
            [gh, jnp.zeros((R - n, gh.shape[1]), dtype=gh.dtype)],
            axis=0)

    return obs_compile.instrument_jit("serial.pad_gh", pad)


def _maybe_rand_bins(extra_trees: bool, rand_seed, node_id, meta, params):
    """Per-node extra_trees random thresholds, or None."""
    if not extra_trees:
        return None
    key = jax.random.fold_in(jax.random.PRNGKey(rand_seed), node_id)
    return make_rand_bins(key, meta, params)


class BundleTables(NamedTuple):
    """Device-resident EFB tables (io/efb.py BundleLayout mirror).
    ``member[g, b]``/``unmap[g, b]`` route a bundle bin back to its
    owning feature and original bin; ``gidx_*`` gather the bundle
    histogram into per-feature histograms; zero rows are reconstructed
    for ``zero_fix`` features."""
    group_of: jnp.ndarray       # [Fp] i32
    member: jnp.ndarray         # [Gp, Bg] i32
    unmap: jnp.ndarray          # [Gp, Bg] i32
    gidx_g: jnp.ndarray         # [Fp, B] i32 (-1 = empty)
    gidx_b: jnp.ndarray         # [Fp, B] i32
    zero_fix: jnp.ndarray       # [Fp] bool


def _leaf_histogram(bins, gh, meta, btab, *, B: int, Bg: int,
                    bundled: bool, totals=None,
                    hist_impl: tuple = ("auto", False)):
    """Histogram of (a subset of) rows → per-feature [Fp, B, 4].
    Bundled mode histograms the [*, G] bundle matrix at Bg bins then
    unpacks (totals = the leaf's channel sums for zero-bin rows; must
    match the histogram dtype — quantized integer gh recomputes the
    exact int sums here when the caller only holds dequantized f32)."""
    if not bundled:
        return build_histogram(bins, gh, B, hist_impl=hist_impl)
    bhist = build_histogram(bins, gh, Bg, hist_impl=hist_impl)
    if totals is None or jnp.issubdtype(gh.dtype, jnp.integer):
        totals = sum_gh(gh)
    return unpack_bundle_histogram(bhist, btab.gidx_g, btab.gidx_b,
                                   btab.zero_fix, meta.zero_bin, totals)


def build_bundle_tables(dataset: BinnedDataset, Fp: int, Gp: int,
                        B: int, Bg: int) -> BundleTables:
    """Device EFB tables from the dataset's BundleLayout, padded to
    ``Fp`` features / ``Gp`` bundle columns (shared by the serial and
    mesh-parallel learners)."""
    lay = dataset.bundle
    F = dataset.num_features
    G = lay.num_groups
    member = np.full((Gp, Bg), -1, dtype=np.int32)
    member[:G, :lay.member.shape[1]] = lay.member
    unmap = np.zeros((Gp, Bg), dtype=np.int32)
    unmap[:G, :lay.unmap.shape[1]] = lay.unmap
    group_of = np.zeros(Fp, dtype=np.int32)
    group_of[:F] = lay.group_of
    gidx_g = np.full((Fp, B), -1, dtype=np.int32)
    gidx_b = np.zeros((Fp, B), dtype=np.int32)
    gidx_g[:F, :lay.gidx_g.shape[1]] = lay.gidx_g
    gidx_b[:F, :lay.gidx_b.shape[1]] = lay.gidx_b
    zero_fix = np.zeros(Fp, dtype=bool)
    zero_fix[:F] = lay.needs_zero_fix
    return BundleTables(
        group_of=jnp.asarray(group_of), member=jnp.asarray(member),
        unmap=jnp.asarray(unmap), gidx_g=jnp.asarray(gidx_g),
        gidx_b=jnp.asarray(gidx_b), zero_fix=jnp.asarray(zero_fix))


def _partition_col(bins, f, meta, btab, bundled: bool):
    """The split feature's ORIGINAL bin value per row (unbundling via the
    member/unmap LUTs when bundled; identity otherwise)."""
    if not bundled:
        return jnp.take(bins, f, axis=1).astype(jnp.int32)
    g = btab.group_of[f]
    raw = jnp.take(bins, g, axis=1).astype(jnp.int32)
    owner = btab.member[g][raw]
    return jnp.where(owner == f, btab.unmap[g][raw], meta.zero_bin[f])


def _finish_split(state: GrowState, rec: SplitRecord, leaf, new_leaf,
                  valid, hist_left, hist_right, mask_left, mask_right,
                  meta, params, *, max_depth: int, extra_trees: bool,
                  has_cat: bool, rand_seed=0, pen_left=None,
                  pen_right=None, children_allowed=None,
                  qscale=None) -> GrowState:
    """Depth gating + both children's best-split scans + candidate
    stores — the split-step tail shared verbatim by the serial and
    mesh-parallel learners (only the child-histogram computation
    differs). ``children_allowed`` None means: derive from the
    device-side leaf_depth against the static max_depth."""
    child_depth = state.leaf_depth[leaf] + 1
    leaf_depth = state.leaf_depth \
        .at[leaf].set(jnp.where(valid, child_depth,
                                state.leaf_depth[leaf])) \
        .at[new_leaf].set(jnp.where(valid, child_depth,
                                    state.leaf_depth[new_leaf]))
    if children_allowed is None:
        children_allowed = (max_depth <= 0) | (child_depth < max_depth)

    left_info = find_best_split(
        hist_left, rec.left_sum_grad, rec.left_sum_hess,
        rec.left_count, rec.left_total_count, meta, params,
        mask_left, state.cand_left_min[leaf],
        state.cand_left_max[leaf],
        parent_output=rec.left_output,
        rand_bins=_maybe_rand_bins(extra_trees, rand_seed, 2 * new_leaf,
                                   meta, params),
        gain_penalty=pen_left, leaf_depth=child_depth,
        has_categorical=has_cat, hist_scale=qscale)
    right_info = find_best_split(
        hist_right, rec.right_sum_grad, rec.right_sum_hess,
        rec.right_count, rec.right_total_count, meta, params,
        mask_right, state.cand_right_min[leaf],
        state.cand_right_max[leaf],
        parent_output=rec.right_output,
        rand_bins=_maybe_rand_bins(extra_trees, rand_seed,
                                   2 * new_leaf + 1, meta, params),
        gain_penalty=pen_right, leaf_depth=child_depth,
        has_categorical=has_cat, hist_scale=qscale)

    state = state._replace(leaf_depth=leaf_depth)
    state = _store_info(state, leaf, left_info, children_allowed, valid)
    state = _store_info(state, new_leaf, right_info, children_allowed,
                        valid)
    return state


def _split_body(bins, state: GrowState, rec: SplitRecord, leaf, new_leaf,
                valid, mask_left, mask_right, meta, params, btab, *,
                S, B: int, Bg: int, bundled: bool, max_depth: int,
                extra_trees: bool, has_cat: bool = True,
                hist_impl: tuple = ("auto", False), children_allowed=None,
                rand_seed=0, pen_left=None, pen_right=None,
                qscale=None) -> GrowState:
    """Apply one split (already chosen: ``rec`` at ``leaf``) and scan both
    children. Shared by the per-split, batched and fused paths.
    ``children_allowed`` None means: derive from device leaf_depth.

    ``S`` is the smaller-child gather size: a static int on the
    host-stepped paths (the host buckets it per batch), or a static
    tuple of bucket sizes on the fused whole-tree path — the device
    then picks the branch of a ``lax.switch`` ladder from the record's
    own child count. Fill rows hit the gh-zero dummy row, so the
    gather size selects compiled programs, never values."""
    R = bins.shape[0]
    f = jnp.maximum(rec.feature, 0)
    col = _partition_col(bins, f, meta, btab, bundled)
    gl = _go_left_by_bin(col, rec.threshold_bin, rec.default_left,
                         meta.missing_type[f], meta.num_bin[f] - 1,
                         meta.zero_bin[f], rec.is_categorical,
                         rec.cat_mask)
    on_leaf = state.leaf_of_row == leaf
    leaf_of_row = jnp.where(valid & on_leaf & ~gl, new_leaf,
                            state.leaf_of_row)

    smaller_is_left = rec.left_total_count <= rec.right_total_count
    small_id = jnp.where(smaller_is_left, leaf, new_leaf)
    small_totals = jnp.stack([
        jnp.where(smaller_is_left, rec.left_sum_grad, rec.right_sum_grad),
        jnp.where(smaller_is_left, rec.left_sum_hess, rec.right_sum_hess),
        jnp.where(smaller_is_left, rec.left_count, rec.right_count),
        jnp.where(smaller_is_left, rec.left_total_count,
                  rec.right_total_count)])

    # quantized mode: the record's totals are dequantized f32, but the
    # bundled zero-bin fix needs exact int sums — _leaf_histogram
    # recomputes them from the gathered integer rows
    def hist_at(size: int):
        (idx,) = jnp.nonzero(leaf_of_row == small_id, size=size,
                             fill_value=R - 1)
        return _leaf_histogram(bins[idx], state.gh[idx], meta, btab,
                               B=B, Bg=Bg, bundled=bundled,
                               totals=small_totals,
                               hist_impl=hist_impl)

    ladder = S if isinstance(S, tuple) else (S,)
    if len(ladder) == 1:
        hist_small = hist_at(ladder[0])
    else:
        # device-side bucket choice (the host `_bucket` policy, on
        # device): smallest ladder size ≥ child count + the f32-count
        # rounding margin; the ladder tops out at next_pow2(N), which
        # covers any child
        small_cnt = small_totals[3]
        k = jnp.clip(
            jnp.sum(jnp.asarray(ladder, dtype=jnp.float32)
                    < small_cnt + 16.0),
            0, len(ladder) - 1).astype(jnp.int32)
        hist_small = jax.lax.switch(
            k, [lambda _, s=s: hist_at(s) for s in ladder], 0)
    hist_large = subtract_histogram(state.hists[leaf], hist_small)
    hist_left = jnp.where(smaller_is_left, hist_small, hist_large)
    hist_right = jnp.where(smaller_is_left, hist_large, hist_small)
    hists = state.hists \
        .at[leaf].set(jnp.where(valid, hist_left, state.hists[leaf])) \
        .at[new_leaf].set(
            jnp.where(valid, hist_right, state.hists[new_leaf]))

    state = state._replace(leaf_of_row=leaf_of_row, hists=hists)
    return _finish_split(state, rec, leaf, new_leaf, valid, hist_left,
                         hist_right, mask_left, mask_right, meta, params,
                         max_depth=max_depth, extra_trees=extra_trees,
                         has_cat=has_cat, rand_seed=rand_seed,
                         pen_left=pen_left, pen_right=pen_right,
                         children_allowed=children_allowed,
                         qscale=qscale)


@functools.lru_cache(maxsize=None)
def _root_fn_cached(L: int, B: int, Bg: int, bundled: bool,
                    extra_trees: bool, has_cat: bool = True,
                    hist_impl: tuple = ("auto", False)):
    def root(bins, gh, leaf_of_row0, feature_mask, children_allowed,
             rand_seed, qscale, meta, params, btab):
        F = meta.num_bin.shape[0]
        sums_raw = sum_gh(gh)          # exact ints in quantized mode
        hist = _leaf_histogram(bins, gh, meta, btab, B=B, Bg=Bg,
                               bundled=bundled, totals=sums_raw,
                               hist_impl=hist_impl)
        sums = dequantize_sums(sums_raw, qscale)
        # root "parent" output: its own unsmoothed output (reference:
        # SerialTreeLearner::GetParentOutput, serial_tree_learner.cpp:786)
        parent_out = calculate_leaf_output(sums[0], sums[1], params)
        info = find_best_split(
            hist, sums[0], sums[1], sums[2], sums[3], meta, params,
            feature_mask, parent_output=parent_out,
            rand_bins=_maybe_rand_bins(extra_trees, rand_seed, 0, meta,
                                       params),
            leaf_depth=jnp.int32(0), has_categorical=has_cat,
            hist_scale=qscale)
        state = make_root_state(gh, hist, leaf_of_row0, info, L, F, B,
                                children_allowed)
        return state, _record_at(state, 0)

    return obs_compile.instrument_jit("serial.root", root)


@functools.lru_cache(maxsize=None)
def _step_fn_cached(S: int, B: int, Bg: int, bundled: bool,
                    extra_trees: bool, has_cat: bool = True,
                    hist_impl: tuple = ("auto", False)):
    """Per-split step (host chooses the leaf): used when per-node feature
    masks (interaction constraints / bynode sampling) force a host
    round-trip per split."""
    def step(bins, state: GrowState, leaf, new_leaf, children_allowed,
             mask_left, mask_right, rand_seed, qscale, meta, params,
             btab):
        rec = _record_at(state, leaf)
        state = _split_body(bins, state, rec, leaf, new_leaf,
                            jnp.asarray(True), mask_left, mask_right,
                            meta, params, btab, S=S, B=B, Bg=Bg,
                            bundled=bundled, max_depth=0,
                            extra_trees=extra_trees, has_cat=has_cat,
                            hist_impl=hist_impl,
                            children_allowed=children_allowed,
                            rand_seed=rand_seed, qscale=qscale)
        best = jnp.argmax(state.gain).astype(jnp.int32)
        return state, _record_at(state, best)

    return obs_compile.instrument_jit("serial.step", step,
                                      donate_argnums=(1,))


def _cegb_penalty(params, count, used, coupled, unfetched, lazy):
    """Per-feature CEGB gain penalty for scanning one leaf (reference:
    CostEfficientGradientBoosting::DeltaGain,
    cost_effective_gradient_boosting.hpp:80-99): split penalty scaled by
    leaf size + coupled penalty for model-new features + lazy per-row
    fetch cost for rows that have not used the feature yet."""
    pen = params.cegb_penalty_split * count + coupled * (~used)
    if lazy is not None:
        pen = pen + lazy * unfetched
    return params.cegb_tradeoff * pen


@functools.lru_cache(maxsize=None)
def _cegb_root_fn_cached(L: int, B: int, Bg: int, bundled: bool,
                         has_lazy: bool, has_cat: bool = True,
                         hist_impl: tuple = ("auto", False)):
    def root(bins, gh, leaf_of_row0, feature_mask, children_allowed,
             used, fetched, coupled, lazy, qscale, meta, params, btab):
        F = meta.num_bin.shape[0]
        sums_raw = sum_gh(gh)
        hist = _leaf_histogram(bins, gh, meta, btab, B=B, Bg=Bg,
                               bundled=bundled, totals=sums_raw,
                               hist_impl=hist_impl)
        sums = dequantize_sums(sums_raw, qscale)
        parent_out = calculate_leaf_output(sums[0], sums[1], params)
        if has_lazy:
            in_rows = (leaf_of_row0 >= 0).astype(jnp.float32)
            unfetched = jnp.einsum("r,rf->f", in_rows, 1.0 - fetched)
        else:
            unfetched, lazy = None, None
        pen = _cegb_penalty(params, sums[3], used, coupled, unfetched,
                            lazy)
        info = find_best_split(
            hist, sums[0], sums[1], sums[2], sums[3], meta, params,
            feature_mask, parent_output=parent_out, gain_penalty=pen,
            has_categorical=has_cat, hist_scale=qscale)
        state = make_root_state(gh, hist, leaf_of_row0, info, L, F, B,
                                children_allowed)
        return state, _record_at(state, 0)

    return obs_compile.instrument_jit("serial.cegb_root", root)


@functools.lru_cache(maxsize=None)
def _cegb_step_fn_cached(S: int, B: int, Bg: int, bundled: bool,
                         has_lazy: bool, has_cat: bool = True,
                         hist_impl: tuple = ("auto", False)):
    """Per-split CEGB step: applies the pending split, updates the
    used-features vector and (lazy mode) the per-(row, feature) fetched
    matrix, and scans both children with penalized gains (reference:
    SerialTreeLearner::Split + CEGB UpdateLeafBestSplits,
    cost_effective_gradient_boosting.hpp:101). Divergence from the
    reference: candidates stored for *other* leaves are not retroactively
    refunded when a coupled feature first becomes used — they keep the
    penalty until re-scanned as children (pessimistic ordering only)."""
    def step(bins, state: GrowState, leaf, new_leaf, children_allowed,
             feature_mask, used, fetched, coupled, lazy, qscale, meta,
             params, btab):
        rec = _record_at(state, leaf)
        f = jnp.maximum(rec.feature, 0)
        used2 = used.at[f].set(True)
        on_leaf = state.leaf_of_row == leaf
        if has_lazy:
            # every row that flowed through the new split node has now
            # "fetched" feature f (both children)
            fetched2 = jnp.maximum(
                fetched,
                on_leaf.astype(fetched.dtype)[:, None]
                * jax.nn.one_hot(f, fetched.shape[1],
                                 dtype=fetched.dtype))
            col = _partition_col(bins, f, meta, btab, bundled)
            gl = _go_left_by_bin(col, rec.threshold_bin, rec.default_left,
                                 meta.missing_type[f],
                                 meta.num_bin[f] - 1, meta.zero_bin[f],
                                 rec.is_categorical, rec.cat_mask)
            unf = 1.0 - fetched2
            unf_left = jnp.einsum(
                "r,rf->f", (on_leaf & gl).astype(jnp.float32), unf)
            unf_right = jnp.einsum(
                "r,rf->f", (on_leaf & ~gl).astype(jnp.float32), unf)
        else:
            fetched2 = fetched
            unf_left = unf_right = None
            lazy = None
        pen_l = _cegb_penalty(params, rec.left_total_count, used2,
                              coupled, unf_left, lazy)
        pen_r = _cegb_penalty(params, rec.right_total_count, used2,
                              coupled, unf_right, lazy)
        state = _split_body(bins, state, rec, leaf, new_leaf,
                            jnp.asarray(True), feature_mask, feature_mask,
                            meta, params, btab, S=S, B=B, Bg=Bg,
                            bundled=bundled, max_depth=0,
                            extra_trees=False, has_cat=has_cat,
                            hist_impl=hist_impl,
                            children_allowed=children_allowed,
                            pen_left=pen_l, pen_right=pen_r,
                            qscale=qscale)
        best = jnp.argmax(state.gain).astype(jnp.int32)
        return state, _record_at(state, best), used2, fetched2

    return obs_compile.instrument_jit("serial.cegb_step", step,
                                      donate_argnums=(1,))


@functools.lru_cache(maxsize=None)
def _mono_step_fn_cached(S: int, B: int, Bg: int, bundled: bool,
                         has_cat: bool = True,
                         hist_impl: tuple = ("auto", False)):
    """Per-split step for monotone_constraints_method=intermediate: the
    children's output bounds come from the host tracker (sibling-output
    based, monotone_constraints.hpp:543) instead of the mid-point rule
    baked into the stored candidate."""
    def step(bins, state: GrowState, leaf, new_leaf, children_allowed,
             feature_mask, lmin, lmax, rmin, rmax, qscale, meta, params,
             btab):
        state = state._replace(
            cand_left_min=state.cand_left_min.at[leaf].set(lmin),
            cand_left_max=state.cand_left_max.at[leaf].set(lmax),
            cand_right_min=state.cand_right_min.at[leaf].set(rmin),
            cand_right_max=state.cand_right_max.at[leaf].set(rmax))
        rec = _record_at(state, leaf)
        state = _split_body(bins, state, rec, leaf, new_leaf,
                            jnp.asarray(True), feature_mask, feature_mask,
                            meta, params, btab, S=S, B=B, Bg=Bg,
                            bundled=bundled, max_depth=0,
                            extra_trees=False, has_cat=has_cat,
                            hist_impl=hist_impl,
                            children_allowed=children_allowed,
                            qscale=qscale)
        best = jnp.argmax(state.gain).astype(jnp.int32)
        return state, _record_at(state, best), state.gain

    return obs_compile.instrument_jit("serial.mono_step", step,
                                      donate_argnums=(1,))


@functools.lru_cache(maxsize=None)
def _rescan_fn_cached(B: int, has_cat: bool = True):
    """Recompute one leaf's best-split candidate from its stored
    histogram under tightened output bounds (reference:
    SerialTreeLearner::RecomputeBestSplitForLeaf,
    serial_tree_learner.cpp:800)."""
    def rescan(state: GrowState, leaf, sg, sh, c, tc, vmin, vmax, depth,
               allowed, feature_mask, qscale, meta, params, btab):
        hist = state.hists[leaf]
        own = calculate_leaf_output(sg, sh, params)
        parent_out = jnp.where(params.path_smooth > 1e-10, own, 0.0)
        info = find_best_split(hist, sg, sh, c, tc, meta, params,
                               feature_mask, vmin, vmax,
                               parent_output=parent_out,
                               leaf_depth=depth,
                               has_categorical=has_cat,
                               hist_scale=qscale)
        state = _store_info(state, leaf, info, allowed)
        best = jnp.argmax(state.gain).astype(jnp.int32)
        return state, _record_at(state, best), state.gain

    return obs_compile.instrument_jit("serial.rescan", rescan,
                                      donate_argnums=(0,))


@functools.lru_cache(maxsize=None)
def _adv_rescan_fn_cached(B: int, has_cat: bool = True):
    """monotone_constraints_method=advanced candidate scan: the leaf's
    per-(feature, bin) constraint arrays replace the leaf-wide bound
    pair (reference: AdvancedLeafConstraints feeding FindBestThreshold
    through CumulativeFeatureConstraint,
    monotone_constraints.hpp:856-1184 + feature_histogram.hpp:874-951)."""
    def rescan(state: GrowState, leaf, sg, sh, c, tc, min_c, max_c,
               depth, allowed, feature_mask, qscale, meta, params, btab):
        hist = state.hists[leaf]
        own = calculate_leaf_output(sg, sh, params)
        parent_out = jnp.where(params.path_smooth > 1e-10, own, 0.0)
        info = find_best_split(hist, sg, sh, c, tc, meta, params,
                               feature_mask,
                               parent_output=parent_out,
                               leaf_depth=depth,
                               has_categorical=has_cat,
                               bound_arrays=(min_c, max_c),
                               hist_scale=qscale)
        state = _store_info(state, leaf, info, allowed)
        best = jnp.argmax(state.gain).astype(jnp.int32)
        return state, _record_at(state, best), state.gain

    return obs_compile.instrument_jit("serial.adv_rescan", rescan,
                                      donate_argnums=(0,))


@functools.lru_cache(maxsize=None)
def _forced_fn_cached(S: int, B: int, Bg: int, bundled: bool,
                      extra_trees: bool, has_cat: bool = True,
                      hist_impl: tuple = ("auto", False)):
    """Forced split of a given (feature, threshold-bin) on a leaf
    (reference: SerialTreeLearner::ForceSplits,
    serial_tree_learner.cpp:451): the split record is built from the
    leaf's stored histogram instead of a best-gain scan, then applied
    through the normal split body so the children get their candidate
    scans."""
    def forced(bins, state: GrowState, leaf, new_leaf, f, tbin,
               children_allowed, feature_mask, rand_seed, qscale, meta,
               params, btab):
        row = state.hists[leaf][f]                   # [B, 4]
        cum = jnp.cumsum(row, axis=0)                # exact when integer
        tot = cum[-1]
        left = dequantize_sums(cum[tbin], qscale)
        right = dequantize_sums(tot, qscale) - left
        out_l = calculate_leaf_output(left[0], left[1], params)
        out_r = calculate_leaf_output(right[0], right[1], params)
        # default_left must match where the cumsum put the missing rows:
        # ZERO rows sit in the zero bin (left iff zero_bin <= tbin), NaN
        # rows in the last bin (left iff tbin reaches it) — same
        # convention as find_best_split's natural placement
        dl = jnp.where(meta.missing_type[f] == MissingType.NAN,
                       tbin >= meta.num_bin[f] - 1,
                       meta.zero_bin[f] <= tbin)
        rec = SplitRecord(
            leaf=leaf, gain=jnp.float32(0.0), feature=f,
            threshold_bin=tbin, default_left=dl,
            is_categorical=jnp.asarray(False),
            cat_mask=jnp.zeros(B, dtype=bool),
            left_sum_grad=left[0], left_sum_hess=left[1],
            left_count=left[2], left_total_count=left[3],
            left_output=out_l,
            right_sum_grad=right[0], right_sum_hess=right[1],
            right_count=right[2], right_total_count=right[3],
            right_output=out_r)
        ok = (left[3] > 0.5) & (right[3] > 0.5)
        state = _split_body(bins, state, rec, leaf, new_leaf, ok,
                            feature_mask, feature_mask, meta, params,
                            btab, S=S, B=B, Bg=Bg, bundled=bundled,
                            max_depth=0, extra_trees=extra_trees,
                            has_cat=has_cat, hist_impl=hist_impl,
                            children_allowed=children_allowed,
                            rand_seed=rand_seed, qscale=qscale)
        return state, rec, ok

    return obs_compile.instrument_jit("serial.forced", forced,
                                      donate_argnums=(1,))


@functools.lru_cache(maxsize=None)
def _batch_fn_cached(S: int, kb: int, B: int, Bg: int, bundled: bool,
                     max_depth: int, extra_trees: bool,
                     has_cat: bool = True,
                     hist_impl: tuple = ("auto", False)):
    """Batched split steps: one dispatch runs kb splits, the device
    picking the best leaf each step (the argmax the reference does on host
    at serial_tree_learner.cpp:194). Records of the applied splits are
    written to [kb] buffers and read back once."""
    def batch(bins, state: GrowState, start_leaf, max_splits,
              feature_mask, rand_seed, qscale, meta, params, btab):
        def body(i, carry):
            state, recs = carry
            best = jnp.argmax(state.gain).astype(jnp.int32)
            rec = _record_at(state, best)
            valid = rec_valid(rec) & (i < max_splits)
            recs = jax.tree_util.tree_map(
                lambda buf, v: buf.at[i].set(v), recs, rec)
            new_leaf = (start_leaf + i).astype(jnp.int32)
            state = _split_body(bins, state, rec, best, new_leaf, valid,
                                feature_mask, feature_mask, meta, params,
                                btab, S=S, B=B, Bg=Bg, bundled=bundled,
                                max_depth=max_depth,
                                extra_trees=extra_trees, has_cat=has_cat,
                                hist_impl=hist_impl,
                                rand_seed=rand_seed, qscale=qscale)
            return state, recs

        state, recs = jax.lax.fori_loop(
            0, kb, body, (state, _empty_records(kb, B)))
        return state, recs

    return obs_compile.instrument_jit("serial.batch", batch,
                                      donate_argnums=(1,))


def _bucket_ladder(bucket_fn, max_bucket: int) -> tuple:
    """Every gather size ``bucket_fn`` can return, ascending — the
    static branch ladder of the fused whole-tree grower. Each branch
    compiles one child-histogram gather size; the padded fill rows
    carry gh 0, so which branch runs changes compiled programs, never
    values."""
    sizes = {bucket_fn(0.0)}
    c = 1
    while c <= max_bucket:
        sizes.add(bucket_fn(float(c)))
        c <<= 1
    return tuple(sorted(sizes))


@functools.lru_cache(maxsize=None)
def _fused_fn_cached(L: int, B: int, Bg: int, bundled: bool,
                     max_depth: int, extra_trees: bool,
                     has_cat: bool = True,
                     hist_impl: tuple = ("auto", False),
                     ladder: tuple = ()):
    """Fused whole-tree growth: ONE dispatch runs the entire grow loop
    — the device argmaxes the next frontier leaf, applies the split
    (partition update + smaller-child histogram through the gather
    ladder + sibling subtraction), scans both children, and appends the
    record — until no positive-gain candidate remains. The host reads
    back only the [L-1] record buffer (the Booster-paper /
    XGBoost-GPU "whole pipeline on the accelerator" move; the serial
    analogue of the mesh learner's `_tree_impl`). Bit-identical to the
    stepped `serial.batch` loop: same body, same per-step argmax, same
    gather semantics."""
    kb = L - 1

    def fused(bins, state: GrowState, start_leaf, max_splits,
              feature_mask, rand_seed, qscale, meta, params, btab):
        def cond(carry):
            i, _, _, cont = carry
            return cont & (i < kb)

        def body(carry):
            i, state, recs, _ = carry
            best = jnp.argmax(state.gain).astype(jnp.int32)
            rec = _record_at(state, best)
            valid = rec_valid(rec) & (i < max_splits)
            recs = jax.tree_util.tree_map(
                lambda buf, v: buf.at[i].set(v), recs, rec)
            new_leaf = (start_leaf + i).astype(jnp.int32)
            state = _split_body(bins, state, rec, best, new_leaf, valid,
                                feature_mask, feature_mask, meta, params,
                                btab, S=ladder, B=B, Bg=Bg,
                                bundled=bundled, max_depth=max_depth,
                                extra_trees=extra_trees, has_cat=has_cat,
                                hist_impl=hist_impl,
                                rand_seed=rand_seed, qscale=qscale)
            return i + 1, state, recs, valid

        carry = (jnp.int32(0), state, _empty_records(kb, B),
                 jnp.asarray(True))
        _, state, recs, _ = jax.lax.while_loop(cond, body, carry)
        return state, recs

    return obs_compile.instrument_jit("serial.fused_tree", fused,
                                      donate_argnums=(1,))


class SerialTreeLearner(CapabilityMixin):
    """Leaf-wise grower over a device-resident binned dataset."""

    def __init__(self, config, dataset: BinnedDataset):
        self.config = config
        self.dataset = dataset
        N = dataset.num_data
        F = dataset.num_features  # logical features (≠ bundle columns)
        if F == 0:
            log.fatal("Cannot train without features")
        self.N, self.F = N, F
        # pad the histogram width to a power of two: the actual max bin
        # count is data-dependent (e.g. 251 vs 247), and a canonical B
        # lets datasets with similar binning share compiled step variants
        self.B = _next_pow2(max(int(dataset.max_num_bin), 2))
        self.L = int(config.num_leaves)
        self.max_depth = int(config.max_depth)
        # Pad rows to a 4096 multiple (at least one dummy row) and
        # feature/bundle columns to an 8 multiple: pad rows carry gh 0 /
        # leaf -1 so they vanish from every sum, pad features are trivial
        # (num_bin 1), and the canonical shapes share compiled step
        # variants across datasets. The dummy rows double as the
        # nonzero-gather fill target.
        self.R = -(-(N + 1) // 4096) * 4096
        self.Fp = -(-F // 8) * 8
        from ..ops.histogram import resolve_hist_impl
        qbits = (int(getattr(config, "quant_grad_bits", 8))
                 if getattr(config, "use_quantized_grad", False) else 0)
        self._hist_impl = resolve_hist_impl(
            getattr(config, "hist_backend", "auto"),
            bool(getattr(config, "tpu_use_f64_hist", False)), qbits)
        self._init_quantization(self._hist_impl[2], config, N)
        self._bundled = dataset.bundle is not None
        ncols = (dataset.bundle.num_groups if self._bundled else F)
        self.Gp = -(-ncols // 8) * 8
        bins_host = np.zeros((self.R, self.Gp if self._bundled
                              else self.Fp), dtype=dataset.bins.dtype)
        bins_host[:N, :ncols if self._bundled else F] = dataset.bins
        with obs.scope("io::stage_bins_device"):
            self.bins = jnp.asarray(bins_host)
        self._leaf_of_row0 = jnp.concatenate([
            jnp.zeros(N, dtype=jnp.int32),
            jnp.full((self.R - N,), -1, dtype=jnp.int32)])
        # all-rows in-bag indicator, staged once (per-tree creation
        # would be an implicit scalar transfer per tree)
        self._ones_ind = jnp.ones(N, dtype=jnp.float32)
        from ..ops.split import pad_feature_meta
        self.meta = pad_feature_meta(
            FeatureMeta.from_dataset(dataset,
                                     int(config.max_cat_to_onehot)),
            self.Fp - F)
        self._build_bundle_tables(dataset)
        self.params = SplitParams.from_config(config)
        self._ff_rng = np.random.RandomState(config.feature_fraction_seed)
        self._resolve_constraints()
        self._max_bucket = _next_pow2(N)
        # fused whole-tree growth (default): the entire grow loop runs
        # as one dispatch; the stepped per-batch host loop stays behind
        # the flag (and under the host-stepped capability drivers)
        self._fused_growth = bool(getattr(config, "tpu_fused_tree", True))
        self._ladder = _bucket_ladder(self._bucket, self._max_bucket)
        # extra_trees (config.h:368): random single-threshold candidates,
        # seeded per tree (host counter) and per node (device fold-in)
        self._extra_trees = bool(config.extra_trees)
        self._extra_seed = int(config.extra_seed)
        self._tree_idx = 0
        # STATIC: all-numerical datasets compile out the categorical
        # scans entirely (two argsorts + a sequential 256-step lax.scan
        # per leaf scan)
        self._has_cat = bool(np.asarray(self.meta.is_categorical).any())
        self._root_fn = _root_fn_cached(self.L, self.B, self.Bg,
                                        self._bundled, self._extra_trees,
                                        self._has_cat, self._hist_impl)
        self._forced = self._load_forced_splits(config)
        self._init_cegb(config)
        self._init_monotone(config)

    # _sample_features lives on CapabilityMixin (shared with the
    # sharded out-of-core learner, treelearner/sharded.py)

    # ------------------------------------------------------------------
    def _build_bundle_tables(self, dataset: BinnedDataset) -> None:
        """Device EFB tables (or a dummy scalar when unbundled)."""
        if not self._bundled:
            self.Bg = 0
            self._btab = jnp.int32(0)
            return
        self.Bg = _next_pow2(max(dataset.bundle.num_bundled_bins, 2))
        self._btab = build_bundle_tables(dataset, self.Fp, self.Gp,
                                         self.B, self.Bg)

    def _step_fn(self, S: int):
        return _step_fn_cached(S, self.B, self.Bg, self._bundled,
                               self._extra_trees, self._has_cat,
                               self._hist_impl)

    def _batch_fn(self, S: int):
        kb = self._batch_k(S)
        return (_batch_fn_cached(S, kb, self.B, self.Bg, self._bundled,
                                 self.max_depth, self._extra_trees,
                                 self._has_cat, self._hist_impl), kb)

    def _fused_fn(self):
        return _fused_fn_cached(self.L, self.B, self.Bg, self._bundled,
                                self.max_depth, self._extra_trees,
                                self._has_cat, self._hist_impl,
                                self._ladder)

    def _batch_k(self, S: int) -> int:
        """Steps per dispatch: aim for ~4R gathered rows per batch so early
        (large-S) batches stay short while deep-tree batches amortize the
        host round-trip over many cheap steps. Derived from the padded row
        count R (not N) so the (S, kb) pair — and thus the compiled batch
        variant — is shared across datasets of similar size."""
        return int(np.clip((4 * self.R) // max(S, 1), 1, _MAX_BATCH))

    def _bucket(self, count: float) -> int:
        # Small data (one pad block): a single canonical gather size —
        # every small dataset then shares one compiled batch variant, and
        # the extra gathered rows are noise at this scale.
        if self.R <= 4096:
            return self.R // 2
        # +16 margin: counts travel as f32 sums and may round for very
        # large leaves. The floor caps compiled variants at ~log2(N) - 8.
        S = min(max(_next_pow2(int(count) + 16), _MIN_BUCKET),
                self._max_bucket)
        if self._max_bucket >= (1 << 20):
            # large datasets: even power-of-two exponents only — halves
            # the number of compiled batch variants (each is a slow
            # remote compile on the TPU tunnel) for ≤2x gather slack
            e = S.bit_length() - 1
            if (e & 1) and S < self._max_bucket:
                S <<= 1
        return min(S, self._max_bucket)

    # ------------------------------------------------------------------
    def _load_forced_splits(self, config):
        """Parse forcedsplits_filename JSON (reference: forced splits
        config.h:518, format {"feature": i, "threshold": v,
        "left": {...}, "right": {...}})."""
        if not config.forcedsplits_filename:
            return None
        import json
        try:
            with open(config.forcedsplits_filename) as fh:
                return json.load(fh)
        except (OSError, ValueError) as e:
            log.warning("Cannot load forced splits from %s: %s"
                        % (config.forcedsplits_filename, e))
            return None

    def _apply_forced_splits(self, tree: Tree, state: GrowState,
                             feature_mask, rand_seed, leaf_total):
        """Apply the forced-split tree breadth-first before best-gain
        growth (reference: SerialTreeLearner::ForceSplits,
        serial_tree_learner.cpp:451). Returns (state, next_leaf)."""
        next_leaf = 1
        queue = [(0, self._forced)]
        while queue and next_leaf < self.L:
            leaf, spec = queue.pop(0)
            if not isinstance(spec, dict) or "feature" not in spec:
                continue
            inner = self.dataset.inner_feature_index(int(spec["feature"]))
            if inner < 0:
                continue
            mapper = self.dataset.bin_mappers[inner]
            tbin = int(mapper.value_to_bin(
                np.asarray([float(spec.get("threshold", 0.0))]))[0])
            M = max(leaf_total.values())
            S = self._bucket(M / 2)
            fn = _forced_fn_cached(S, self.B, self.Bg, self._bundled,
                                   self._extra_trees, self._has_cat,
                                   self._hist_impl)
            allowed = self._splittable(int(tree.leaf_depth[leaf]) + 1)
            state, rec, ok = fn(self.bins, state, jnp.int32(leaf),
                                jnp.int32(next_leaf), jnp.int32(inner),
                                jnp.int32(tbin), jnp.asarray(allowed),
                                feature_mask, rand_seed, self._qscale,
                                self.meta, self.params, self._btab)
            # jaxlint: disable=JLT001 -- forced splits are a host-
            # driven preamble (the host must validate each user-forced
            # split before recording it); runs once per tree root area
            if not bool(jax.device_get(ok)):
                log.warning("Forced split on feature %d leaves an empty "
                            "side; skipped" % int(spec["feature"]))
                continue
            # jaxlint: disable=JLT001 -- forced-split record read-back
            # (host Tree replay), same preamble as above
            r = jax.device_get(rec)
            apply_split_record(tree, self.dataset, r)
            leaf_total[leaf] = float(r.left_total_count)
            leaf_total[next_leaf] = float(r.right_total_count)
            if "left" in spec:
                queue.append((leaf, spec["left"]))
            if "right" in spec:
                queue.append((next_leaf, spec["right"]))
            next_leaf += 1
        return state, next_leaf

    def _splittable(self, depth: int) -> bool:
        return self.max_depth <= 0 or depth < self.max_depth

    def train(self, grad: jnp.ndarray, hess: jnp.ndarray,
              bag: Optional[jnp.ndarray] = None
              ) -> Tuple[Tree, jnp.ndarray]:
        """Grow one tree. ``grad``/``hess`` are f32[N] device arrays;
        ``bag`` an optional f32[N] in-bag indicator (0/1). Returns the host
        Tree and the final [N] row→leaf assignment (device) for score
        updates (reference: GBDT::UpdateScore uses the learner's partition,
        src/boosting/gbdt.cpp:475)."""
        with obs.scope("tree::stage_gh"):
            ind = self._ones_ind if bag is None else bag
            if self._quantized:
                gh, self._qscale = self._quantize_stage(
                    grad, hess, ind, self._tree_idx + 1)
                gh = _pad_rows_fn_cached(self.R)(gh)
            else:
                self._qscale = self._qs_ones
                # one fused dispatch for stack+pad: the former eager
                # jnp.ones/stack/concatenate chain performed implicit
                # scalar transfers each tree (transfer-guard sanitizer)
                gh = _stage_gh_fn_cached(self.R)(grad, hess, ind)
            # fencing mode blocks here so the staging cost lands in THIS
            # stage; sample/trace mode hands the output to the async
            # readiness drainer instead (no hot-path fence)
            obs.watch_ready("tree::stage_gh", gh)
            feature_mask = self._sample_features()

        tree = Tree(self.L)
        # per-tree extra_trees seed (traced, so no retrace per tree);
        # explicit device transfer — see utils/scalars.py
        self._tree_idx += 1
        rand_seed = dev_i32(
            (self._extra_seed + 7919 * self._tree_idx) & 0x7FFFFFFF)
        if self._cegb_enabled:
            state = train_cegb(self, tree, gh, feature_mask)
            return tree, _rows_out_fn_cached(self.N)(state.leaf_of_row)
        if self._mono_tracker is not None:
            state = train_monotone(self, tree, gh, feature_mask,
                                   rand_seed)
            return tree, _rows_out_fn_cached(self.N)(state.leaf_of_row)
        with obs.scope("tree::root_histogram"):
            state, rec = self._root_fn(self.bins, gh, self._leaf_of_row0,
                                       feature_mask,
                                       dev_bool(self._splittable(0)),
                                       rand_seed, self._qscale, self.meta,
                                       self.params, self._btab)
            obs.watch_ready("tree::root_histogram", rec)
        leaf_total = {0: float(self.N)}
        next_leaf = 1
        if self._forced is not None:
            state, next_leaf = self._apply_forced_splits(
                tree, state, feature_mask, rand_seed, leaf_total)
        per_node = self._needs_per_node_masks()
        if per_node and self._forced is not None:
            log.warning("forced splits combined with per-node feature "
                        "masks run without the per-node masks")
        if per_node and self._forced is None:
            state = train_stepwise(self, tree, state, rec, feature_mask,
                                   rand_seed)
        elif self._fused_growth:
            state = self._train_fused(tree, state, feature_mask,
                                      rand_seed, next_leaf)
        else:
            state = self._train_batched(tree, state, feature_mask,
                                        rand_seed, leaf_total, next_leaf)
        return tree, _rows_out_fn_cached(self.N)(state.leaf_of_row)

    # ------------------------------------------------------------------
    def _train_fused(self, tree: Tree, state: GrowState, feature_mask,
                     rand_seed, next_leaf: int = 1) -> GrowState:
        """Whole-tree device growth: one `serial.fused_tree` dispatch,
        one record read-back (vs one per ~kb-split batch on the stepped
        path). `next_leaf` > 1 continues after a forced-split
        preamble."""
        max_splits = self.L - next_leaf
        if max_splits <= 0:
            return state
        fn = self._fused_fn()
        with obs.scope("tree::split_batches"):
            state, recs = fn(self.bins, state, dev_i32(next_leaf),
                             dev_i32(max_splits), feature_mask,
                             rand_seed, self._qscale, self.meta,
                             self.params, self._btab)
            # jaxlint: disable=JLT001 -- THE per-tree host sync of the
            # fused path: the whole tree's split records read back in
            # one deliberate hop (the grow loop itself never syncs)
            recs_h = jax.device_get(recs)
        with obs.scope("tree::apply_records"):
            for i in range(max_splits):
                r = jax.tree_util.tree_map(lambda a: a[i], recs_h)
                if not record_is_valid(r):
                    break
                apply_split_record(tree, self.dataset, r)
        return state

    # ------------------------------------------------------------------
    def _train_batched(self, tree: Tree, state: GrowState,
                       feature_mask, rand_seed, leaf_total=None,
                       next_leaf: int = 1) -> GrowState:
        if leaf_total is None:
            leaf_total = {0: float(self.N)}
        while next_leaf < self.L:
            M = max(leaf_total.values())
            S = self._bucket(M / 2)
            fn, kb = self._batch_fn(S)
            max_splits = min(kb, self.L - next_leaf)
            # split_batches = per-leaf child histogram + best-split scan
            # steps fused into one dispatch; the device_get is the
            # per-batch sync, so the scope covers the real device time
            with obs.scope("tree::split_batches"):
                state, recs = fn(self.bins, state, dev_i32(next_leaf),
                                 dev_i32(max_splits), feature_mask,
                                 rand_seed, self._qscale, self.meta,
                                 self.params, self._btab)
                # jaxlint: disable=JLT001 -- the LEGACY stepped path's
                # per-batch host sync (tpu_fused_tree=false; also the
                # fused path's bit-parity reference): the split records
                # must reach the host Tree once per ~log2(L) batch
                recs_h = jax.device_get(recs)
            stop = False
            with obs.scope("tree::apply_records"):
                for i in range(max_splits):
                    r = jax.tree_util.tree_map(lambda a: a[i], recs_h)
                    if not record_is_valid(r):
                        stop = True
                        break
                    apply_split_record(tree, self.dataset, r)
                    leaf_total[int(r.leaf)] = float(r.left_total_count)
                    leaf_total[next_leaf] = float(r.right_total_count)
                    next_leaf += 1
            if stop:
                break
        return state

    # --- adapter methods for the shared capability drivers
    # (treelearner/capabilities.py): each wraps this learner's cached
    # jitted step functions with its bucketed gather size ---------------

    def _cegb_root(self, gh, feature_mask):
        root = _cegb_root_fn_cached(self.L, self.B, self.Bg,
                                    self._bundled, self._cegb_has_lazy,
                                    self._has_cat, self._hist_impl)
        return root(self.bins, gh, self._leaf_of_row0, feature_mask,
                    self._splittable(0), self._cegb_used,
                    self._cegb_fetched, self._cegb_coupled,
                    self._cegb_lazy, self._qscale, self.meta,
                    self.params, self._btab)

    def _cegb_step(self, state, leaf, k, allowed, feature_mask, smaller):
        S = self._bucket(smaller)
        fn = _cegb_step_fn_cached(S, self.B, self.Bg, self._bundled,
                                  self._cegb_has_lazy,
                                  self._has_cat, self._hist_impl)
        state, rec, self._cegb_used, self._cegb_fetched = fn(
            self.bins, state, jnp.int32(leaf), jnp.int32(k),
            jnp.asarray(allowed), feature_mask,
            self._cegb_used, self._cegb_fetched, self._cegb_coupled,
            self._cegb_lazy, self._qscale, self.meta, self.params,
            self._btab)
        return state, rec

    def _mono_root(self, gh, feature_mask, rand_seed):
        # extra_trees is ignored on this path — the root scan must be
        # greedy too, not just the step scans
        root_fn = _root_fn_cached(self.L, self.B, self.Bg, self._bundled,
                                  False, self._has_cat, self._hist_impl)
        return root_fn(self.bins, gh, self._leaf_of_row0, feature_mask,
                       self._splittable(0), rand_seed, self._qscale,
                       self.meta, self.params, self._btab)

    def _mono_step(self, state, leaf, k, allowed, feature_mask, bounds,
                   smaller):
        S = self._bucket(smaller)
        fn = _mono_step_fn_cached(S, self.B, self.Bg, self._bundled,
                                  self._has_cat, self._hist_impl)
        return fn(self.bins, state, jnp.int32(leaf), jnp.int32(k),
                  jnp.asarray(allowed), feature_mask,
                  jnp.float32(bounds[0]), jnp.float32(bounds[1]),
                  jnp.float32(bounds[2]), jnp.float32(bounds[3]),
                  self._qscale, self.meta, self.params, self._btab)

    def _mono_rescan(self, state, leaf, sums, entry, depth, allowed,
                     feature_mask):
        rescan = _rescan_fn_cached(self.B, self._has_cat)
        sg, sh, c, tc = sums
        return rescan(state, jnp.int32(leaf), jnp.float32(sg),
                      jnp.float32(sh), jnp.float32(c), jnp.float32(tc),
                      jnp.float32(entry[0]), jnp.float32(entry[1]),
                      jnp.int32(depth), jnp.asarray(allowed),
                      feature_mask, self._qscale, self.meta, self.params,
                      self._btab)

    def _adv_scan(self, state, leaf, sums, bound_arrays, depth, allowed,
                  feature_mask):
        fn = _adv_rescan_fn_cached(self.B, self._has_cat)
        sg, sh, c, tc = sums
        min_c, max_c = bound_arrays
        return fn(state, jnp.int32(leaf), jnp.float32(sg),
                  jnp.float32(sh), jnp.float32(c), jnp.float32(tc),
                  jnp.asarray(min_c), jnp.asarray(max_c),
                  jnp.int32(depth), jnp.asarray(allowed), feature_mask,
                  self._qscale, self.meta, self.params, self._btab)

    def _node_step(self, state, leaf, k, allowed, mask_left, mask_right,
                   rand_seed, smaller):
        S = self._bucket(smaller)
        return self._step_fn(S)(
            self.bins, state, jnp.int32(leaf), jnp.int32(k),
            jnp.asarray(allowed), mask_left, mask_right, rand_seed,
            self._qscale, self.meta, self.params, self._btab)
