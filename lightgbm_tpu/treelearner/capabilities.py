"""Learner-independent capability layer: config resolution + the
host-side training loops that need per-split host state.

The reference supports every feature (CEGB, monotone constraint methods,
extra_trees, interaction constraints, per-node column sampling) under
every ``tree_learner`` — the feature logic lives in shared classes the
learners all call (reference: src/treelearner/col_sampler.hpp,
cost_effective_gradient_boosting.hpp, monotone_constraints.hpp). This
module is the TPU build's equivalent: the config-derived feature state
(:class:`CapabilityMixin`) and the three host drivers that steer
per-split device steps (CEGB penalties, intermediate-monotone bound
propagation, per-node feature masks) are written once and used by both
the single-chip :class:`~.serial.SerialTreeLearner` and the
mesh-parallel learners (parallel/data_parallel.py), which plug in their
own jitted step functions via the ``_cegb_root/_cegb_step``,
``_mono_root/_mono_step/_mono_rescan`` and ``_node_step`` adapter
methods.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import log


class CapabilityMixin:
    """Config-derived feature state shared by all tree learners.

    Requires the concrete learner to define, before the ``_init_*``
    calls: ``config``, ``dataset``, ``F`` (logical features), ``Fp``
    (padded feature axis of masks/penalty vectors), ``L``, ``R``,
    ``_ff_rng``, ``_extra_trees``.
    """

    # the voting learner keeps no per-leaf histogram store, so the
    # intermediate monotone method's rescans are impossible there
    _supports_intermediate = True

    # ------------------------------------------------------------------
    def _resolve_constraints(self):
        """interaction_constraints (config.h:562): groups of inner feature
        indices; a branch may only combine features co-occurring in at
        least one group (reference: ColSampler::SetUsedFeatureByNode)."""
        ic = self.config.interaction_constraints
        if not ic:
            self._constraint_groups = None
            return
        groups = []
        for grp in ic:
            inner = set()
            for real_f in grp:
                j = self.dataset.inner_feature_index(int(real_f))
                if j >= 0:
                    inner.add(j)
            if inner:
                groups.append(frozenset(inner))
        self._constraint_groups = groups or None

    def _node_mask(self, tree_mask: jnp.ndarray,
                   path_features: frozenset) -> jnp.ndarray:
        """Per-node mask: interaction constraints filtered by the
        feature-path, plus feature_fraction_bynode sampling."""
        mask = None
        if self._constraint_groups is not None:
            allowed = np.zeros(self.Fp, dtype=bool)
            for grp in self._constraint_groups:
                if path_features <= grp:
                    allowed[list(grp)] = True
            mask = allowed
        ffb = float(self.config.feature_fraction_bynode)
        if 0.0 < ffb < 1.0:
            n_real = self.dataset.num_features
            m2 = np.zeros(self.Fp, dtype=bool)
            k = max(1, int(round(n_real * ffb)))
            m2[self._ff_rng.choice(n_real, k, replace=False)] = True
            mask = m2 if mask is None else (mask & m2)
        if mask is None:
            return tree_mask
        return tree_mask & jnp.asarray(mask)

    def _needs_per_node_masks(self) -> bool:
        return (self._constraint_groups is not None
                or 0.0 < float(self.config.feature_fraction_bynode) < 1.0)

    def _sample_features(self) -> jnp.ndarray:
        """Per-tree column sampling (reference: ColSampler,
        src/treelearner/col_sampler.hpp:20). Shared by the serial and
        sharded learners — the host RNG sequence is part of the
        bit-parity contract between them."""
        ff = float(self.config.feature_fraction)
        mask = np.zeros(self.Fp, dtype=bool)
        mask[:self.F] = True
        if 0.0 < ff < 1.0:
            k = max(1, int(round(self.F * ff)))
            mask[:] = False
            mask[self._ff_rng.choice(self.F, k, replace=False)] = True
        if self._constraint_groups is not None:
            allowed = np.zeros(self.Fp, dtype=bool)
            for grp in self._constraint_groups:
                allowed[list(grp)] = True
            mask &= allowed
        return jnp.asarray(mask)

    # ------------------------------------------------------------------
    def _init_quantization(self, qbits: int, config, max_rows: int
                           ) -> None:
        """Quantized-gradient mode state (ops/quantize.py), shared by
        the serial and mesh learners: the static per-row magnitude cap
        (overflow discipline vs the histogram accumulator), the row
        dtype, and the per-tree PRNG seed for stochastic rounding.
        ``self._qscale`` always holds the CURRENT tree's (g, h) scales —
        ones in exact mode — so every step adapter can pass it
        unconditionally."""
        self._quantized = bool(qbits)
        self._qs_ones = jnp.ones(2, dtype=jnp.float32)
        self._qscale = self._qs_ones
        if not self._quantized:
            return
        from ..ops.quantize import (effective_quant_max, quant_dtype,
                                    quant_warn_capped)
        self._qmax = effective_quant_max(qbits, max_rows)
        self._qdtype = quant_dtype(qbits)
        quant_warn_capped(qbits, self._qmax, max_rows)
        self._quant_seed = int(getattr(config, "seed", 0)) & 0x7FFFFFFF
        # base key staged once at setup: a per-tree PRNGKey(seed) would
        # be an implicit scalar transfer inside the training loop
        self._quant_base_key = jax.random.PRNGKey(self._quant_seed)
        # device-side tree counter: the per-tree fold-in value now
        # advances ON DEVICE (ops/quantize.tree_key), so steady-state
        # training performs zero per-tree seed transfers (each new
        # tree number used to be a fresh dev_u32 device_put). The host
        # mirror below tracks the same sequence without ever reading
        # the device value back — it exists only to ASSERT the counter
        # stays in lockstep with the callers' tree numbering.
        from ..utils.scalars import dev_u32
        self._quant_ctr = dev_u32(0)
        self._quant_ctr_host = 0

    def _quantize_stage(self, grad, hess, ind, tree_no: int):
        """Discretize one tree's (grad, hess, in-bag) to integer rows.
        The draw runs on the UNPADDED [N] vectors with a per-tree
        fold-in key, so learners with different row/feature padding
        (serial pads rows to 4096s, meshes to the device count) produce
        BIT-IDENTICAL quantized rows — the padding-invariance contract
        make_rand_bins established for extra_trees. The key derives
        from the device-side counter (``tree_key``); the assert pins
        its sequence to the caller's ``tree_no`` (1, 2, ...) — a
        caller off the one-call-per-tree cadence would otherwise
        silently shift every later stochastic draw."""
        from ..ops.quantize import quantize_gh, tree_key
        key, self._quant_ctr = tree_key(self._quant_base_key,
                                        self._quant_ctr)
        self._quant_ctr_host += 1
        assert self._quant_ctr_host == tree_no, \
            "quantize tree counter desynced from tree numbering " \
            "(%d != %d)" % (self._quant_ctr_host, tree_no)
        return quantize_gh(grad, hess, ind, key, self._qmax,
                           self._qdtype)

    # ------------------------------------------------------------------
    def _make_cegb_fetched(self, rows: int) -> jnp.ndarray:
        """[rows, Fp] zeros for the lazy-penalty fetched matrix; mesh
        learners override to create it row-sharded."""
        return jnp.zeros((rows, self.Fp), dtype=jnp.float32)

    def _init_cegb(self, config) -> None:
        """CEGB setup (reference: CostEfficientGradientBoosting::IsEnable
        + Init, cost_effective_gradient_boosting.hpp:27-68). The
        used-features vector and (lazy mode) the per-(row, feature)
        fetched matrix persist across trees, like the reference's
        is_feature_used_in_split_ / feature_used_in_data_ members."""
        coupled = list(config.cegb_penalty_feature_coupled or [])
        lazy = list(config.cegb_penalty_feature_lazy or [])
        self._cegb_enabled = (config.cegb_tradeoff < 1.0
                              or config.cegb_penalty_split > 0.0
                              or bool(coupled) or bool(lazy))
        if not self._cegb_enabled:
            return
        if self._extra_trees:
            log.warning("extra_trees is ignored when CEGB is enabled")
        n_total = self.dataset.num_total_features
        for name, vec in (("cegb_penalty_feature_coupled", coupled),
                          ("cegb_penalty_feature_lazy", lazy)):
            if vec and len(vec) != n_total:
                log.fatal("%s should be the same size as feature number "
                          "(%d vs %d)" % (name, len(vec), n_total))

        def to_inner(vec):
            out = np.zeros(self.Fp, dtype=np.float32)
            if vec:
                for j in range(self.dataset.num_features):
                    out[j] = vec[self.dataset.real_feature_index(j)]
            return jnp.asarray(out)

        self._cegb_coupled = to_inner(coupled)
        self._cegb_lazy = to_inner(lazy)
        self._cegb_has_lazy = bool(lazy) and any(v != 0 for v in lazy)
        self._cegb_used = jnp.zeros(self.Fp, dtype=bool)
        if self._cegb_has_lazy:
            if self.R * self.Fp > 3 * 10**8:
                log.warning("cegb_penalty_feature_lazy tracks a "
                            "[rows x features] matrix (%.1f GB)"
                            % (self.R * self.Fp * 4 / 2**30))
            self._cegb_fetched = self._make_cegb_fetched(self.R)
        else:
            self._cegb_fetched = self._make_cegb_fetched(1)

    # ------------------------------------------------------------------
    def _init_monotone(self, config) -> None:
        """intermediate/advanced monotone methods route through the
        host-tracked stepwise path (reference: the LeafConstraintsBase
        hierarchy, monotone_constraints.hpp)."""
        self._mono_tracker = None
        method = str(config.monotone_constraints_method)
        mc = self.dataset.monotone_constraints
        has_mono = mc is not None and any(int(v) != 0 for v in mc)
        if not has_mono or method == "basic":
            return
        if self._cegb_enabled:
            log.warning("CEGB takes precedence over "
                        "monotone_constraints_method=%s; monotone "
                        "constraints run in basic mode" % method)
            return
        if not self._supports_intermediate:
            log.warning("monotone_constraints_method=%s degrades to "
                        "'basic' under the voting-parallel learner (no "
                        "per-leaf histogram store to rescan)" % method)
            return
        if self._extra_trees:
            log.warning("extra_trees is ignored under "
                        "monotone_constraints_method=%s" % method)
        n_real = self.dataset.num_features
        mono_inner = np.zeros(self.Fp, dtype=np.int8)
        mono_inner[:n_real] = np.asarray(mc, dtype=np.int8)[:n_real]
        if method == "advanced":
            from .monotone import AdvancedMonotoneTracker
            num_bin = np.ones(self.Fp, dtype=np.int64)
            nbpf = self.dataset.num_bin_per_feature
            num_bin[:len(nbpf)] = nbpf
            self._mono_tracker = AdvancedMonotoneTracker(
                self.L, mono_inner, num_bin, self.B)
        else:
            from .monotone import IntermediateMonotoneTracker
            self._mono_tracker = IntermediateMonotoneTracker(self.L,
                                                             mono_inner)


# ----------------------------------------------------------------------
# Host-side training drivers. Each steers per-split device steps through
# the learner's adapter methods; the loops are identical for the serial
# and mesh learners (the reference runs one loop too — the learners only
# differ below FindBestSplits, serial_tree_learner.cpp:159).
# ----------------------------------------------------------------------

def train_cegb(learner, tree, gh, feature_mask):
    """CEGB growth: one host round-trip per split so penalties track
    the evolving used/fetched state (reference: the DeltaGain calls
    inside FindBestSplitsFromHistograms, serial_tree_learner.cpp:375+)."""
    from .serial import apply_split_record, record_is_valid

    if getattr(learner, "_forced", None) is not None \
            or learner._constraint_groups is not None:
        log.warning("CEGB runs without forced splits / per-node "
                    "feature masks")
    state, rec = learner._cegb_root(gh, feature_mask)
    # jaxlint: disable=JLT001 -- CEGB is a host-stepped driver: the
    # per-feature penalty depends on host used/fetched state, so one
    # sync per split is the documented contract of this mode
    pending = jax.device_get(rec)
    for k in range(1, learner.L):
        if not record_is_valid(pending):
            break
        leaf = int(pending.leaf)
        apply_split_record(tree, learner.dataset, pending)
        allowed = learner._splittable(int(tree.leaf_depth[leaf]))
        smaller = min(float(pending.left_total_count),
                      float(pending.right_total_count))
        state, rec = learner._cegb_step(state, leaf, k, allowed,
                                        feature_mask, smaller)
        # jaxlint: disable=JLT001 -- per-split sync (CEGB host loop)
        pending = jax.device_get(rec)
    return state


def train_monotone(learner, tree, gh, feature_mask, rand_seed):
    """monotone_constraints_method=intermediate/advanced growth:
    stepwise with host-tracked bounds + contiguous-leaf rescans
    (reference: SerialTreeLearner::Split → constraints_->Update →
    RecomputeBestSplitForLeaf, serial_tree_learner.cpp:702-710).

    The advanced method additionally recomputes both fresh children
    with their per-(feature, bin) constraint arrays (the reference's
    lazily-recomputed AdvancedLeafConstraints,
    monotone_constraints.hpp:856) — the scalar-bound candidates from
    the shared step are overwritten by an ``_adv_scan`` per child."""
    from .monotone import AdvancedMonotoneTracker
    from .serial import apply_split_record, record_is_valid

    tracker = learner._mono_tracker
    advanced = isinstance(tracker, AdvancedMonotoneTracker)
    tracker.reset()
    if getattr(learner, "_forced", None) is not None:
        log.warning("forced splits are ignored under "
                    "monotone_constraints_method=%s"
                    % learner.config.monotone_constraints_method)
    if learner._constraint_groups is not None:
        log.warning("interaction constraints are ignored under "
                    "monotone_constraints_method=%s"
                    % learner.config.monotone_constraints_method)
    state, rec = learner._mono_root(gh, feature_mask, rand_seed)
    # jaxlint: disable=JLT001 -- intermediate/advanced monotone growth
    # is host-stepped (bound propagation walks the host tree); one
    # sync per split is the mode's documented contract
    pending = jax.device_get(rec)
    gains_h = None
    leaf_sums: dict = {}
    for k in range(1, learner.L):
        if not record_is_valid(pending):
            break
        leaf = int(pending.leaf)
        f_inner = int(pending.feature)
        mono_type = int(tracker.mono[f_inner])
        if leaf == 0 and 0 not in leaf_sums:
            leaf_sums[0] = (
                float(pending.left_sum_grad)
                + float(pending.right_sum_grad),
                float(pending.left_sum_hess)
                + float(pending.right_sum_hess),
                float(pending.left_count)
                + float(pending.right_count),
                float(pending.left_total_count)
                + float(pending.right_total_count))
        tracker.before_split(tree, leaf, mono_type)
        apply_split_record(tree, learner.dataset, pending)
        lo, ro = float(pending.left_output), \
            float(pending.right_output)
        applied_numerical = not bool(pending.is_categorical)
        if advanced:
            tracker.apply_split_outputs(leaf, k, mono_type, lo, ro,
                                        applied_numerical)
            bounds = (-np.inf, np.inf, -np.inf, np.inf)
        else:
            bounds = tracker.child_bounds(leaf, mono_type, lo, ro)
            tracker.apply_split(tree, leaf, k, bounds)
        leaf_sums[leaf] = (float(pending.left_sum_grad),
                           float(pending.left_sum_hess),
                           float(pending.left_count),
                           float(pending.left_total_count))
        leaf_sums[k] = (float(pending.right_sum_grad),
                        float(pending.right_sum_hess),
                        float(pending.right_count),
                        float(pending.right_total_count))
        allowed = learner._splittable(int(tree.leaf_depth[leaf]))
        smaller = min(float(pending.left_total_count),
                      float(pending.right_total_count))
        applied_tbin = int(pending.threshold_bin)
        state, rec, gains_d = learner._mono_step(
            state, leaf, k, allowed, feature_mask, bounds, smaller)
        if advanced:
            # overwrite both children's candidates with the
            # per-threshold-constrained scan
            for child in (leaf, k):
                d = int(tree.leaf_depth[child])
                arrs = tracker.leaf_bound_arrays(tree, child)
                state, rec, gains_d = learner._adv_scan(
                    state, child, leaf_sums[child], arrs, d,
                    learner._splittable(d), feature_mask)
        # jaxlint: disable=JLT001 -- per-split sync (monotone host loop)
        pending, gains_h = jax.device_get((rec, gains_d))
        # propagate to contiguous leaves + rescan them
        upd = tracker.leaves_to_update(
            tree, k, f_inner, applied_tbin, lo, ro,
            applied_numerical,
            lambda l: (l <= k and np.isfinite(gains_h[l])))
        for l in upd:
            allowed_l = learner._splittable(int(tree.leaf_depth[l]))
            if advanced:
                arrs = tracker.leaf_bound_arrays(tree, l)
                state, rec, gains_d = learner._adv_scan(
                    state, l, leaf_sums[l], arrs,
                    int(tree.leaf_depth[l]), allowed_l, feature_mask)
            else:
                emin, emax = tracker.entries[l]
                state, rec, gains_d = learner._mono_rescan(
                    state, l, leaf_sums[l], (emin, emax),
                    int(tree.leaf_depth[l]), allowed_l, feature_mask)
        if upd:
            # jaxlint: disable=JLT001 -- re-sync after constrained
            # rescans of updated leaves (monotone host loop)
            pending, gains_h = jax.device_get((rec, gains_d))
    return state


def train_stepwise(learner, tree, state, rec, feature_mask, rand_seed=0):
    """One host round-trip per split — needed when per-node feature
    masks depend on the host-side feature path."""
    from .serial import apply_split_record, record_is_valid

    # jaxlint: disable=JLT001 -- per-node feature masks are computed
    # from the host-side feature path, so this driver syncs per split
    # by design (its docstring is the contract)
    pending = jax.device_get(rec)
    paths = {0: frozenset()}
    for k in range(1, learner.L):
        if not record_is_valid(pending):
            break
        leaf = int(pending.leaf)
        f = int(pending.feature)
        apply_split_record(tree, learner.dataset, pending)
        allowed = learner._splittable(int(tree.leaf_depth[leaf]))
        smaller = min(float(pending.left_total_count),
                      float(pending.right_total_count))
        paths[leaf] = paths[k] = paths.get(leaf, frozenset()) | {f}
        mask_left = learner._node_mask(feature_mask, paths[leaf])
        mask_right = learner._node_mask(feature_mask, paths[k])
        state, rec = learner._node_step(state, leaf, k, allowed,
                                        mask_left, mask_right, rand_seed,
                                        smaller)
        # jaxlint: disable=JLT001 -- per-split sync (stepwise host loop)
        pending = jax.device_get(rec)
    return state
