"""Tree-learner factory.

Equivalent of the reference's ``TreeLearner::CreateTreeLearner``
(reference: src/treelearner/tree_learner.cpp:15-55 — keyed on
``tree_learner`` ∈ serial/feature/data/voting × ``device_type``). On TPU
the device dimension collapses: every learner runs on the accelerator;
the parallel variants differ only in how they shard over the mesh.
"""
from __future__ import annotations

from ..utils import log
from .serial import SerialTreeLearner


def create_tree_learner(config, dataset, mesh=None):
    name = getattr(config, "tree_learner", "serial")
    from ..io.shards import ShardedBinnedDataset
    if isinstance(dataset, ShardedBinnedDataset):
        # out-of-core datasets have exactly one engine: the shard-sweep
        # learner (treelearner/sharded.py). Its trees are pinned
        # bit-identical to serial, so the promotion is silent for the
        # default and a Warning for an explicit mesh-learner ask.
        if name not in ("serial",):
            log.warning("tree_learner=%s requested but the dataset is "
                        "sharded (out-of-core); using the sharded "
                        "shard-sweep learner" % name)
        from .sharded import ShardedTreeLearner
        return ShardedTreeLearner(config, dataset)
    if name in ("serial",):
        # On an accelerator the serial learner's per-split host
        # round-trips dominate (a remote chip charges ~27 ms each; 254
        # splits/tree — measured round 3). The 1-device-mesh data
        # learner grows the whole tree in ONE dispatch and is pinned
        # bit-exact to serial (tests/test_parallel_learners.py), so the
        # DEFAULT promotes — an explicitly requested serial learner is
        # honored, as are forced splits (serial-scan only).
        explicit = any(k in getattr(config, "raw_params", {})
                       for k in ("tree_learner", "tree", "tree_type",
                                 "tree_learner_type"))
        import jax
        if (not explicit and jax.default_backend() != "cpu"
                and not config.forcedsplits_filename):
            from ..parallel import DataParallelTreeLearner, make_mesh
            log.info("tree_learner=serial on an accelerator: using the "
                     "1-device-mesh whole-tree learner (identical "
                     "trees, one host sync per tree instead of one "
                     "per split)")
            return DataParallelTreeLearner(config, dataset, make_mesh(1))
        return SerialTreeLearner(config, dataset)
    import jax
    from ..parallel import (DataParallelTreeLearner,
                            FeatureParallelTreeLearner,
                            VotingParallelTreeLearner, make_mesh)
    if mesh is None:
        if len(jax.devices()) < 2:
            # still honor the request on a 1-device mesh: the mesh
            # learners grow the whole tree in ONE dispatch (one
            # read-back per tree), which also makes them the faster
            # engine when host round-trips dominate (e.g. big-N CPU)
            log.info("tree_learner=%s on a single device: using a "
                     "1-device mesh (whole-tree dispatch)" % name)
        # mesh_shape (e.g. "data=8") bounds the device count; the
        # 1-D GBDT learners use the first axis extent
        n_dev = None
        shape = str(getattr(config, "mesh_shape", "") or "")
        if shape:
            try:
                n_dev = int(shape.split(",")[0].split("=")[1])
            except (IndexError, ValueError):
                log.warning("cannot parse mesh_shape=%r; using all "
                            "devices" % shape)
        mesh = make_mesh(n_dev)
    if name in ("data", "data_parallel"):
        return DataParallelTreeLearner(config, dataset, mesh)
    if name in ("feature", "feature_parallel"):
        return FeatureParallelTreeLearner(config, dataset, mesh)
    if name in ("voting", "voting_parallel"):
        return VotingParallelTreeLearner(config, dataset, mesh)
    log.fatal("Unknown tree learner type %s" % name)


__all__ = ["SerialTreeLearner", "create_tree_learner"]
