from .serial import SerialTreeLearner  # noqa: F401
