"""Advanced features example (reference:
examples/python-guide/advanced_example.py — model management, custom
objective/metric, continued training, parameter reset)."""
import json
import os
import pickle

import numpy as np

import lightgbm_tpu as lgb

HERE = os.path.dirname(os.path.abspath(__file__))
DATA = os.path.join(HERE, os.pardir, "binary_classification")

print("Loading data...")
train = np.loadtxt(os.path.join(DATA, "binary.train"), delimiter="\t")
test = np.loadtxt(os.path.join(DATA, "binary.test"), delimiter="\t")
y_train, X_train = train[:, 0], train[:, 1:]
y_test, X_test = test[:, 0], test[:, 1:]
W_train = np.ones(len(y_train))

lgb_train = lgb.Dataset(X_train, label=y_train, weight=W_train)
lgb_eval = lgb.Dataset(X_test, label=y_test, reference=lgb_train)

params = {"boosting_type": "gbdt", "objective": "binary",
          "metric": "binary_logloss", "num_leaves": 31, "verbose": 0}

evals_result = {}
print("Starting training...")
gbm = lgb.train(params, lgb_train, num_boost_round=10,
                valid_sets=[lgb_train, lgb_eval],
                valid_names=["train", "eval"],
                callbacks=[lgb.record_evaluation(evals_result)])

print("Dumping model to JSON...")
model_json = gbm.dump_model()
with open(os.path.join(HERE, "model.json"), "w") as f:
    json.dump(model_json, f, indent=2)

print(f"Feature names: {gbm.feature_name()}")
print(f"Feature importances: {list(gbm.feature_importance())}")

print("Saving model...")
gbm.save_model(os.path.join(HERE, "model.txt"))
print("Dumping and loading model with pickle...")
with open(os.path.join(HERE, "model.pkl"), "wb") as f:
    pickle.dump(gbm, f)
with open(os.path.join(HERE, "model.pkl"), "rb") as f:
    pkl_bst = pickle.load(f)
y_pred = pkl_bst.predict(X_test, num_iteration=7)
logloss = float(-np.mean(
    y_test * np.log(np.clip(y_pred, 1e-15, 1))
    + (1 - y_test) * np.log(np.clip(1 - y_pred, 1e-15, 1))))
print(f"The logloss of loaded model's prediction is: {logloss}")

print("Continuing training from the saved model...")
gbm = lgb.train(params, lgb_train, num_boost_round=10,
                init_model=os.path.join(HERE, "model.txt"),
                valid_sets=[lgb_eval])

print("Continuing training with parameter reset...")
gbm = lgb.train(dict(params, learning_rate=0.02), lgb_train,
                num_boost_round=10, init_model=gbm,
                valid_sets=[lgb_eval])


# custom objective: log-likelihood loss (same as binary)
def loglikelihood(preds, train_data):
    labels = train_data.get_label()
    preds = 1.0 / (1.0 + np.exp(-preds))
    grad = preds - labels
    hess = preds * (1.0 - preds)
    return grad, hess


# custom metric: error rate
def binary_error(preds, train_data):
    labels = train_data.get_label()
    preds = 1.0 / (1.0 + np.exp(-preds))
    return "error", float(np.mean(labels != (preds > 0.5))), False


print("Starting training with custom objective and eval...")
gbm = lgb.train(dict(params, objective=loglikelihood), lgb_train,
                num_boost_round=10, feval=binary_error,
                valid_sets=[lgb_eval])
print("Finished advanced example.")
