"""Plotting example (reference:
examples/python-guide/plot_example.py — metric curve, importance,
split-value histogram, tree structure). Figures are saved, not shown
(headless)."""
import os

import numpy as np

import lightgbm_tpu as lgb

HERE = os.path.dirname(os.path.abspath(__file__))
DATA = os.path.join(HERE, os.pardir, "regression")

try:
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
except ImportError:
    raise SystemExit("matplotlib is required for plot_example.py")

print("Loading data...")
train = np.loadtxt(os.path.join(DATA, "regression.train"), delimiter="\t")
test = np.loadtxt(os.path.join(DATA, "regression.test"), delimiter="\t")
y_train, X_train = train[:, 0], train[:, 1:]
y_test, X_test = test[:, 0], test[:, 1:]

lgb_train = lgb.Dataset(X_train, label=y_train)
lgb_eval = lgb.Dataset(X_test, label=y_test, reference=lgb_train)

evals_result = {}
print("Starting training...")
gbm = lgb.train({"objective": "regression", "metric": ["l1", "l2"],
                 "num_leaves": 5, "verbose": 0},
                lgb_train, num_boost_round=50,
                valid_sets=[lgb_train, lgb_eval],
                callbacks=[lgb.record_evaluation(evals_result)])

print("Plotting metrics recorded during training...")
ax = lgb.plot_metric(evals_result, metric="l1")
plt.savefig(os.path.join(HERE, "metric.png"))

print("Plotting feature importances...")
ax = lgb.plot_importance(gbm, max_num_features=10)
plt.savefig(os.path.join(HERE, "importance.png"))

print("Plotting split value histogram...")
ax = lgb.plot_split_value_histogram(gbm, feature=2, bins="auto")
plt.savefig(os.path.join(HERE, "split_hist.png"))

print("Plotting 3rd tree...")
try:
    ax = lgb.plot_tree(gbm, tree_index=2, figsize=(15, 8))
    plt.savefig(os.path.join(HERE, "tree.png"))
except ImportError as e:
    print(f"skipping tree plot ({e})")
print("Figures written next to this script.")
