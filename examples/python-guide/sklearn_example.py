"""scikit-learn API example (reference:
examples/python-guide/sklearn_example.py — fit/predict, feature
importances, GridSearchCV)."""
import os

import numpy as np

import lightgbm_tpu as lgb

HERE = os.path.dirname(os.path.abspath(__file__))
DATA = os.path.join(HERE, os.pardir, "regression")

print("Loading data...")
train = np.loadtxt(os.path.join(DATA, "regression.train"), delimiter="\t")
test = np.loadtxt(os.path.join(DATA, "regression.test"), delimiter="\t")
y_train, X_train = train[:, 0], train[:, 1:]
y_test, X_test = test[:, 0], test[:, 1:]

print("Starting training...")
gbm = lgb.LGBMRegressor(num_leaves=31, learning_rate=0.05,
                        n_estimators=40)
gbm.fit(X_train, y_train, eval_set=[(X_test, y_test)],
        eval_metric="l1",
        callbacks=[lgb.early_stopping(stopping_rounds=5)])

print("Starting predicting...")
y_pred = gbm.predict(X_test, num_iteration=gbm.best_iteration_)
rmse = float(np.sqrt(np.mean((y_pred - y_test) ** 2)))
print(f"The RMSE of prediction is: {rmse}")

print(f"Feature importances: {list(gbm.feature_importances_)}")

# self-defined eval metric: root mean squared logarithmic error
def rmsle(y_true, y_pred):
    return ("RMSLE",
            float(np.sqrt(np.mean(
                (np.log1p(np.abs(y_pred)) - np.log1p(np.abs(y_true)))
                ** 2))),
            False)


print("Starting training with custom eval function...")
gbm = lgb.LGBMRegressor(num_leaves=31, learning_rate=0.05,
                        n_estimators=20)
gbm.fit(X_train, y_train, eval_set=[(X_test, y_test)],
        eval_metric=rmsle,
        callbacks=[lgb.early_stopping(stopping_rounds=5)])

try:
    from sklearn.model_selection import GridSearchCV
    print("Grid searching...")
    estimator = lgb.LGBMRegressor(num_leaves=31)
    param_grid = {"learning_rate": [0.01, 0.1], "n_estimators": [20, 40]}
    gbm = GridSearchCV(estimator, param_grid, cv=3)
    gbm.fit(X_train, y_train)
    print(f"Best parameters found by grid search are: {gbm.best_params_}")
except ImportError:
    print("scikit-learn not available; skipping GridSearchCV")
