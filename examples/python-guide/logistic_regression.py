"""Fitting probabilities vs binary outcomes (reference:
examples/python-guide/logistic_regression.py — the xentropy objective
accepts soft labels in [0, 1]; binary requires {0, 1}; both agree on
hard labels)."""
import time

import numpy as np

import lightgbm_tpu as lgb

rng = np.random.RandomState(42)


def experiment(objective, label_type, data):
    np.random.seed(0)
    nrounds = 5
    lgb_data = data[f"lgb_with_{label_type}_labels"]
    params = {"objective": objective, "feature_fraction": 1,
              "bagging_fraction": 1, "verbose": -1}
    time_zero = time.time()
    gbm = lgb.train(params, lgb_data, num_boost_round=nrounds)
    y_fitted_to_binary = gbm.predict(data["X"])
    y_true_binary = data["y_binary"]
    ll = float(-np.mean(
        y_true_binary * np.log(np.clip(y_fitted_to_binary, 1e-15, 1))
        + (1 - y_true_binary)
        * np.log(np.clip(1 - y_fitted_to_binary, 1e-15, 1))))
    return {"time": time.time() - time_zero, "correlation": float(
        np.corrcoef(y_fitted_to_binary, y_true_binary)[0, 1]),
        "logloss": ll}


n = 10000
X = rng.randn(n, 10)
alpha = 1.0 / (1.0 + np.exp(-(X[:, 0] + 0.5 * X[:, 1])))
y_binary = (rng.rand(n) < alpha).astype(float)

data = {
    "X": X,
    "y_probability": alpha,
    "y_binary": y_binary,
    "lgb_with_binary_labels": lgb.Dataset(X, label=y_binary),
    "lgb_with_probability_labels": lgb.Dataset(X, label=alpha),
}

print("Performance of `binary` objective with binary labels:")
print(experiment("binary", "binary", data))
print("Performance of `xentropy` objective with binary labels:")
print(experiment("xentropy", "binary", data))
print("Performance of `xentropy` objective with probability labels:")
print(experiment("xentropy", "probability", data))
