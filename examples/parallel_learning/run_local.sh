#!/bin/bash
# Two-rank fake cluster on localhost (the reference demos the same
# setup in tests/distributed/_test_distributed.py). Each rank is a
# normal CLI invocation; they rendezvous through the jax.distributed
# coordinator (= first machine in mlist.txt).
set -e
cd "$(dirname "$0")"
[ -f ../binary_classification/binary.train ] || python ../generate_data.py
cp -f ../binary_classification/binary.train binary.train
python -m lightgbm_tpu.application config=train.conf local_listen_port=12401 &
RANK1=$!
python -m lightgbm_tpu.application config=train.conf local_listen_port=12400
wait $RANK1
echo "model written by rank 0: LightGBM_model.txt"
