#!/bin/bash
# Train a small model with the Python CLI, then predict from a pure-C
# host through the C ABI (no Python at inference time).
set -euo pipefail
cd "$(dirname "$0")"
export PYTHONPATH="$(cd ../.. && pwd)${PYTHONPATH:+:$PYTHONPATH}"

WORK=${1:-$(mktemp -d)}

# 1. train via the conf-file CLI on the binary_classification example
python ../generate_data.py binary "$WORK" >/dev/null 2>&1 || true
if [ ! -f "$WORK/binary.train" ]; then
  python - "$WORK" <<'EOF'
import sys, numpy as np
work = sys.argv[1]
rng = np.random.RandomState(0)
for name, n in (("binary.train", 1500), ("binary.test", 300)):
    X = rng.randn(n, 8)
    y = (X[:, 0] - X[:, 1] + 0.3 * rng.randn(n) > 0).astype(int)
    np.savetxt("%s/%s" % (work, name),
               np.column_stack([y, X]), delimiter="\t", fmt="%.10g")
EOF
fi
python -m lightgbm_tpu.application task=train objective=binary \
  data="$WORK/binary.train" output_model="$WORK/model.txt" \
  num_trees=20 num_leaves=31 verbosity=-1

# 2. strip the label column for the C host's feature-only CSV
python - "$WORK" <<'EOF'
import sys
import numpy as np
work = sys.argv[1]
rows = np.loadtxt(work + "/binary.test", delimiter="\t")
np.savetxt(work + "/features.csv", rows[:, 1:], delimiter=",", fmt="%.10g")
EOF

# 3. compile the C host (capi.cpp compiled in directly; a shared
#    _capi.so + -l link works identically)
g++ -O2 -std=c++17 -o "$WORK/c_api_example" main.c \
  ../../lightgbm_tpu/native/capi.cpp -lm

# 4. predict from C
"$WORK/c_api_example" "$WORK/model.txt" "$WORK/features.csv" \
  > "$WORK/preds_c.txt"
echo "C predictions written: $WORK/preds_c.txt ($(wc -l < "$WORK/preds_c.txt") rows)"
