/*
 * C host example: load a model file trained by lightgbm_tpu (or by the
 * reference implementation — the text formats interchange) and predict
 * without any Python runtime.
 *
 * Mirrors the call sequence of the reference's C API examples
 * (reference: include/LightGBM/c_api.h usage in tests/c_api_test):
 * create-from-modelfile -> metadata -> PredictForMat (batch) ->
 * PredictForMatSingleRow (serving path) -> free.
 *
 * Build + run: see run.sh (compiles ../../lightgbm_tpu/native/capi.cpp
 * alongside this file; no shared-library install needed).
 *
 * Usage: ./c_api_example <model.txt> <data.csv>
 *   data.csv: comma-separated feature rows, no header, no label column.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "../../lightgbm_tpu/native/capi.h"

#define MAX_COLS 1024

static int read_csv(const char* path, double** out, int* nrow, int* ncol) {
  FILE* f = fopen(path, "r");
  if (!f) return 1;
  double* data = NULL;
  int rows = 0, cols = 0, cap = 0;
  char line[1 << 16];
  while (fgets(line, sizeof(line), f)) {
    double row[MAX_COLS];
    int c = 0;
    for (char* tok = strtok(line, ",\n"); tok && c < MAX_COLS;
         tok = strtok(NULL, ",\n")) {
      row[c++] = atof(tok);
    }
    if (c == 0) continue;
    if (cols == 0) cols = c;
    if (c != cols) { fclose(f); free(data); return 2; }
    if ((rows + 1) * cols > cap) {
      cap = (cap ? cap * 2 : 1024 * cols);
      data = (double*)realloc(data, cap * sizeof(double));
    }
    memcpy(data + (size_t)rows * cols, row, cols * sizeof(double));
    rows++;
  }
  fclose(f);
  *out = data;
  *nrow = rows;
  *ncol = cols;
  return 0;
}

int main(int argc, char** argv) {
  if (argc != 3) {
    fprintf(stderr, "usage: %s <model.txt> <data.csv>\n", argv[0]);
    return 2;
  }
  BoosterHandle booster;
  int num_iterations = 0;
  if (LGBM_BoosterCreateFromModelfile(argv[1], &num_iterations,
                                      &booster) != 0) {
    fprintf(stderr, "load failed: %s\n", LGBM_GetLastError());
    return 1;
  }
  int num_class = 0, num_feature = 0;
  LGBM_BoosterGetNumClasses(booster, &num_class);
  LGBM_BoosterGetNumFeature(booster, &num_feature);
  fprintf(stderr, "model: %d iterations, %d classes, %d features\n",
          num_iterations, num_class, num_feature);

  double* data = NULL;
  int nrow = 0, ncol = 0;
  if (read_csv(argv[2], &data, &nrow, &ncol) != 0) {
    fprintf(stderr, "cannot read %s\n", argv[2]);
    return 1;
  }

  /* batch predict */
  int64_t out_len = 0;
  double* out = (double*)malloc((size_t)nrow * num_class * sizeof(double));
  if (LGBM_BoosterPredictForMat(booster, data, C_API_DTYPE_FLOAT64, nrow,
                                ncol, 1, C_API_PREDICT_NORMAL, 0, -1, "",
                                &out_len, out) != 0) {
    fprintf(stderr, "predict failed: %s\n", LGBM_GetLastError());
    return 1;
  }
  for (int64_t i = 0; i < out_len; ++i) printf("%.17g\n", out[i]);

  /* serving path: single-row call must agree with the batch call */
  double* single = (double*)malloc((size_t)num_class * sizeof(double));
  int64_t single_len = 0;
  if (LGBM_BoosterPredictForMatSingleRow(booster, data,
                                         C_API_DTYPE_FLOAT64, ncol, 1,
                                         C_API_PREDICT_NORMAL, 0, -1, "",
                                         &single_len, single) != 0) {
    fprintf(stderr, "single-row predict failed: %s\n", LGBM_GetLastError());
    return 1;
  }
  if (single_len != num_class || single[0] != out[0]) {
    fprintf(stderr, "single-row mismatch\n");
    return 1;
  }
  free(single);

  free(out);
  free(data);
  LGBM_BoosterFree(booster);
  return 0;
}
