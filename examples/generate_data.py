"""Generate the small synthetic datasets committed under examples/.

The reference ships real sample data (examples/binary_classification/
binary.train etc.); with zero egress here, deterministic synthetic
equivalents are generated instead. Run from the repo root:

    python examples/generate_data.py

Formats follow the reference conventions: TSV, label in column 0, no
header; lambdarank additionally writes ``<file>.query`` with rows per
query (reference: docs on query data / Metadata::SetQuery).
"""
import os

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))


def _write(path, y, X, extra_cols=()):
    arr = np.column_stack([y] + [c for c in extra_cols] + [X])
    np.savetxt(path, arr, delimiter="\t", fmt="%.6g")


def binary(n_train=1000, n_test=300, seed=11):
    rng = np.random.RandomState(seed)
    d = os.path.join(HERE, "binary_classification")
    os.makedirs(d, exist_ok=True)
    for name, n in (("binary.train", n_train), ("binary.test", n_test)):
        X = rng.randn(n, 10)
        X[:, 3] = np.round(np.abs(X[:, 3]) * 2)  # low-cardinality column
        logit = X[:, 0] + 0.8 * X[:, 1] * X[:, 2] - 0.5 * X[:, 3]
        y = (logit + 0.3 * rng.randn(n) > 0).astype(float)
        _write(os.path.join(d, name), y, X)


def regression(n_train=800, n_test=200, seed=12):
    rng = np.random.RandomState(seed)
    d = os.path.join(HERE, "regression")
    os.makedirs(d, exist_ok=True)
    for name, n in (("regression.train", n_train),
                    ("regression.test", n_test)):
        X = rng.randn(n, 8)
        y = (2.0 * X[:, 0] + np.sin(X[:, 1]) + 0.5 * X[:, 2] ** 2
             + 0.1 * rng.randn(n))
        _write(os.path.join(d, name), y, X)


def multiclass(n_train=900, n_test=240, seed=13):
    rng = np.random.RandomState(seed)
    d = os.path.join(HERE, "multiclass_classification")
    os.makedirs(d, exist_ok=True)
    for name, n in (("multiclass.train", n_train),
                    ("multiclass.test", n_test)):
        X = rng.randn(n, 6)
        score = np.stack([X[:, 0] + X[:, 1], X[:, 2] - X[:, 1],
                          0.5 * X[:, 3] + 0.2 * rng.randn(n)], axis=1)
        y = np.argmax(score, axis=1).astype(float)
        _write(os.path.join(d, name), y, X)


def lambdarank(n_queries_train=40, n_queries_test=12, seed=14,
               subdir="lambdarank"):
    rng = np.random.RandomState(seed)
    d = os.path.join(HERE, subdir)
    os.makedirs(d, exist_ok=True)
    for name, nq in (("rank.train", n_queries_train),
                     ("rank.test", n_queries_test)):
        rows, labels, qsizes = [], [], []
        for _ in range(nq):
            sz = rng.randint(8, 25)
            qsizes.append(sz)
            X = rng.randn(sz, 7)
            rel = X[:, 0] + 0.5 * X[:, 1] + 0.3 * rng.randn(sz)
            # graded relevance 0-4 by within-query rank
            order = np.argsort(np.argsort(-rel))
            lab = np.clip(4 - order // max(sz // 5, 1), 0, 4)
            rows.append(X)
            labels.append(lab.astype(float))
        X = np.vstack(rows)
        y = np.concatenate(labels)
        _write(os.path.join(d, name), y, X)
        np.savetxt(os.path.join(d, name + ".query"), np.array(qsizes),
                   fmt="%d")


if __name__ == "__main__":
    binary()
    regression()
    multiclass()
    lambdarank()
    lambdarank(subdir="xendcg")  # same layout, rank_xendcg objective
    print("examples data written under", HERE)
