"""Full-scale AUC parity vs the reference binary (round-5 verdict item
3): same Higgs-shaped data, same params, equal-bins (full-data binning),
equal iteration count; report both test AUCs and the delta.

Usage:
    tools/cpupy.sh tools/parity_run.py [rows] [iters] [ref_bin]

Writes a JSON line and appends a stage log to /tmp/parity_stages.log so
a late failure keeps the evidence.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def log(msg):
    line = "%s %s" % (time.strftime("%H:%M:%S"), msg)
    print(line, flush=True)
    with open("/tmp/parity_stages.log", "a") as f:
        f.write(line + "\n")


def auc(scores, labels):
    order = np.argsort(scores, kind="stable")
    ys = labels[order]
    n1 = ys.sum()
    n0 = len(ys) - n1
    ranks = np.arange(1, len(ys) + 1, dtype=np.float64)
    return float((ranks[ys == 1].sum() - n1 * (n1 + 1) / 2) / (n0 * n1))


def main():
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 10_500_000
    iters = int(sys.argv[2]) if len(sys.argv) > 2 else 50
    ref_bin = sys.argv[3] if len(sys.argv) > 3 else "/tmp/refsrc/lightgbm"
    n_test = min(500_000, rows // 4)
    work = os.environ.get("PARITY_WORKDIR",
                          "/tmp/parity_run_%d" % rows)
    os.makedirs(work, exist_ok=True)

    from bench import make_higgs_like
    log("generating %d train + %d test rows" % (rows, n_test))
    X, y = make_higgs_like(rows, seed=0)
    Xte, yte = make_higgs_like(n_test, seed=99)

    train_tsv = os.path.join(work, "train.tsv")
    test_tsv = os.path.join(work, "test.tsv")
    if not os.path.exists(train_tsv + ".done"):
        log("writing TSVs (reference input)")
        chunk = 1 << 19
        with open(train_tsv, "w") as f:
            for lo in range(0, rows, chunk):
                hi = min(lo + chunk, rows)
                np.savetxt(f, np.column_stack(
                    [y[lo:hi], X[lo:hi]]), delimiter="\t", fmt="%.10g")
        with open(test_tsv, "w") as f:
            np.savetxt(f, np.column_stack([yte, Xte]), delimiter="\t",
                       fmt="%.10g")
        open(train_tsv + ".done", "w").close()

    params_common = [
        "objective=binary", "num_leaves=255", "max_bin=255",
        "learning_rate=0.1", "min_data_in_leaf=100", "verbosity=-1",
        "bin_construct_sample_cnt=%d" % rows,   # full-data binning:
        # deterministic, so both sides build bit-identical BinMappers
        "num_trees=%d" % iters,
    ]
    ref_model = os.path.join(work, "ref_model.txt")
    log("training reference binary (%d iters)" % iters)
    t0 = time.time()
    r = subprocess.run(
        [ref_bin, "task=train", "data=" + train_tsv,
         "output_model=" + ref_model] + params_common,
        capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    t_ref = time.time() - t0
    log("reference trained in %.0fs" % t_ref)
    r = subprocess.run(
        [ref_bin, "task=predict", "data=" + test_tsv,
         "input_model=" + ref_model,
         "output_result=" + os.path.join(work, "ref_preds.txt")],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    ref_pred = np.loadtxt(os.path.join(work, "ref_preds.txt"))
    auc_ref = auc(ref_pred, yte)
    log("reference test AUC %.6f" % auc_ref)

    import lightgbm_tpu as lgb
    params = {
        "objective": "binary", "num_leaves": 255, "max_bin": 255,
        "learning_rate": 0.1, "min_data_in_leaf": 100, "verbosity": -1,
        "bin_construct_sample_cnt": rows,
        "tpu_use_f64_hist": True,   # f32 hist sums drift ~1e-9*N at
        # this scale; f64 accumulation is the documented remedy
        # (reference gpu_use_dp analogue)
    }
    log("training lightgbm_tpu (%d iters)" % iters)
    t0 = time.time()
    ds = lgb.Dataset(X, label=np.asarray(y, dtype=np.float64))
    bst = lgb.train(params, ds, num_boost_round=iters)
    t_ours = time.time() - t0
    log("ours trained in %.0fs" % t_ours)
    ours_pred = bst.predict(Xte)
    auc_ours = auc(ours_pred, yte)
    log("our test AUC %.6f" % auc_ours)

    result = {
        "rows": rows, "iters": iters,
        "auc_ref": round(auc_ref, 7), "auc_ours": round(auc_ours, 7),
        "delta": round(abs(auc_ours - auc_ref), 7),
        "t_ref_s": round(t_ref, 1), "t_ours_s": round(t_ours, 1),
    }
    print(json.dumps(result))
    with open(os.path.join(work, "parity_result.json"), "w") as f:
        json.dump(result, f)
    bst.save_model(os.path.join(work, "our_model.txt"))


if __name__ == "__main__":
    os.environ.setdefault("JAX_ENABLE_X64", "1")
    main()
