#!/bin/bash
# Periodically probe the TPU tunnel; on first success, write a marker
# file so the session knows hardware is reachable. SIGTERM only (a
# SIGKILL on a tunnel holder wedges the relay); generous timeout.
MARKER=${1:-/tmp/tpu_alive}
LOG=${2:-/tmp/tpu_probe_loop.log}
while true; do
  if timeout -s TERM 240 python -c "
import jax
d = jax.devices()
import jax.numpy as jnp
x = jnp.ones((256, 256), dtype=jnp.bfloat16)
(x @ x).block_until_ready()
print('PROBE_OK', d[0].platform, len(d))
" >> "$LOG" 2>&1; then
    date +"%F %T PROBE_OK" >> "$LOG"
    touch "$MARKER"
    exit 0
  fi
  date +"%F %T probe failed; sleeping 480s" >> "$LOG"
  sleep 480
done
