#!/usr/bin/env bash
# One-shot observability gate (CI and pre-push): jaxlint must be clean,
# a traced smoke run must produce VALID compact segments that convert
# losslessly, and the OpenMetrics render/parse pair must round-trip.
# Nonzero exit on the first failure (set -e + explicit asserts).
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
TMP="$(mktemp -d "${TMPDIR:-/tmp}/lgbm_tpu_check.XXXXXX")"
trap 'rm -rf "$TMP"' EXIT

echo "== jaxlint (full rule catalog, incl. JLT008-010 + JLT10x) =="
# Fast pre-commit subset when only touching the threaded modules:
#   python -m tools.jaxlint --select JLT10x lightgbm_tpu/serve lightgbm_tpu/loop
python -m tools.jaxlint lightgbm_tpu

echo "== LOCKTRACE serve smoke (runtime lock sanitizer) =="
# Bounded dynamic leg of the JLT10x family: a warmed PredictServer
# takes an overload burst with every named lock traced — any lock-order
# inversion raises at the acquire, hold-budget overruns fail the
# window assertion.
LIGHTGBM_TPU_LOCKTRACE=1 python - <<'EOF'
import numpy as np

import lightgbm_tpu as lgb
from lightgbm_tpu.serve import PredictServer, StackedForest
from lightgbm_tpu.utils import locktrace

rng = np.random.RandomState(3)
X = rng.randn(512, 6).astype(np.float32).astype(np.float64)
y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
bst = lgb.train({"objective": "binary", "num_leaves": 15,
                 "verbosity": -1, "min_data_in_leaf": 5,
                 "max_bin": 63},
                lgb.Dataset(X, label=y), num_boost_round=8)
srv = PredictServer(StackedForest.from_gbdt(bst), max_batch=32,
                    max_wait_ms=2, max_queue_rows=64, autostart=False)
assert isinstance(srv._cond, locktrace.TracedCondition), \
    "LOCKTRACE did not wrap the server"
srv.start()
try:
    for rows in (1, 8, 32):            # warm every bucket first
        srv.submit(X[:rows]).result(timeout=120)
    locktrace.reset()                  # measured window starts here
    locktrace.tracer().max_hold_s = 2.0
    futs = [srv.submit(X[i % len(X)]) for i in range(256)]
    for f in futs:
        f.exception(timeout=60)        # shed is fine; hangs are not
finally:
    srv.stop()
rep = locktrace.report()
assert rep["acquires"] > 256, rep["acquires"]
locktrace.assert_clean()
print("locktrace ok (%d acquires, %d order edges, 0 violations)"
      % (rep["acquires"], len(rep["edges"])))
EOF

echo "== traced smoke run (compact segments) =="
LIGHTGBM_TPU_TRACE_STREAM="$TMP/trace" \
LIGHTGBM_TPU_TRACE_FORMAT=compact \
LIGHTGBM_TPU_TRACE_SEGMENT_BYTES=65536 \
LIGHTGBM_TPU_TIMETAG=1 \
python - <<'EOF'
import numpy as np
import lightgbm_tpu as lgb
from lightgbm_tpu.obs import trace

rng = np.random.default_rng(0)
X = rng.standard_normal((2000, 10)).astype(np.float32)
y = (X[:, 0] + 0.1 * rng.standard_normal(2000) > 0).astype(np.float32)
lgb.train({"objective": "binary", "num_leaves": 15, "max_bin": 63,
           "verbosity": -1, "min_data_in_leaf": 20},
          lgb.Dataset(X, label=y), num_boost_round=3)
trace.flush()
EOF

python tools/trace_report.py validate "$TMP/trace"
python tools/trace_report.py convert -o "$TMP/converted.json" "$TMP/trace"
python tools/trace_report.py validate "$TMP/converted.json"

echo "== OpenMetrics render/parse round-trip =="
python - <<'EOF'
from lightgbm_tpu.obs.export import render_openmetrics
from lightgbm_tpu.obs.openmetrics import parse_openmetrics, metric_value
from lightgbm_tpu.obs.registry import MetricsRegistry

reg = MetricsRegistry()
reg.enable()
reg.inc("check/widgets", 3)
reg.gauge("check/depth", 7.5)
with reg.scope("check::stage"):
    pass
text = render_openmetrics(reg)
parsed = parse_openmetrics(text)
assert metric_value(parsed, "lightgbm_tpu_check_widgets_total") == 3.0
assert metric_value(parsed, "lightgbm_tpu_check_depth") == 7.5
assert parse_openmetrics(render_openmetrics(reg)) == parsed
print("round-trip ok (%d samples)" % len(parsed))
EOF

echo "== drift smoke (quality plane: clean vs shifted window) =="
# Bounded quality-plane pass: spill a tiny training set (the reference
# profile rides the spill manifest), serve-project it onto a packed
# forest's grid, then score one clean and one covariate-shifted window.
# The clean window must stay under the PSI threshold, the shifted one
# must breach it, and `trace_report.py drift` must agree on both dumps.
DRIFT_CLEAN="$TMP/drift_clean.txt" DRIFT_SHIFT="$TMP/drift_shift.txt" \
python - <<'EOF'
import os
import tempfile

import numpy as np

from lightgbm_tpu.basic import Dataset
from lightgbm_tpu.engine import train
from lightgbm_tpu.io.streaming import StreamingDataset
from lightgbm_tpu.obs.export import render_openmetrics
from lightgbm_tpu.obs.quality import QualityMonitor
from lightgbm_tpu.obs.registry import registry as obs
from lightgbm_tpu.serve.forest import StackedForest

rng = np.random.default_rng(3)
X = rng.normal(size=(2000, 8))
y = (X[:, 0] + 0.5 * X[:, 1] > 0.2).astype(np.float64)
params = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
          "verbosity": -1, "min_data_in_leaf": 10,
          "bin_construct_sample_cnt": 2000}
obs.enable()
sd = StreamingDataset(8, params=params)
for lo in range(0, 2000, 500):
    sd.push_rows(X[lo:lo + 500], label=y[lo:lo + 500])
with tempfile.TemporaryDirectory(prefix="lgbm_tpu_drift_") as spill:
    sharded = sd.finalize(spill_dir=spill, shard_rows=500)
    ds = Dataset(None)
    ds._handle = sharded
    ds.params = dict(params)
    bst = train(dict(params), ds, num_boost_round=3)
profile = getattr(bst.inner, "quality_profile", None)
assert profile is not None, "spill pass produced no reference profile"
profile.attach_scores(np.asarray(bst.inner.train_score,
                                 dtype=np.float32),
                      objective=bst.inner.objective)
forest = StackedForest.from_gbdt(bst)
mon = QualityMonitor(forest, profile=profile)

blk = np.ascontiguousarray(X[:1024], dtype=np.float32)
mon.accumulate(blk, blk.shape[0], device=forest.device)
clean = mon.drain(obs)
assert clean["rows"] == 1024, clean
assert clean["psi_max"] < 0.25, \
    "clean window scored drift: %r" % clean
with open(os.environ["DRIFT_CLEAN"], "w") as f:
    f.write(render_openmetrics(obs))

shifted = np.ascontiguousarray(
    X[:1024] + 2.5 * X.std(axis=0, keepdims=True), dtype=np.float32)
mon.accumulate(shifted, shifted.shape[0], device=forest.device)
drifted = mon.drain(obs)
assert drifted["psi_max"] >= 0.25, \
    "shifted window undetected: %r" % drifted
with open(os.environ["DRIFT_SHIFT"], "w") as f:
    f.write(render_openmetrics(obs))
print("drift smoke ok (clean psi_max %.4f, shifted psi_max %.2f on "
      "feature %s)" % (clean["psi_max"], drifted["psi_max"],
                       drifted["worst_feature"]))
EOF

python tools/trace_report.py drift "$TMP/drift_clean.txt"
if python tools/trace_report.py drift "$TMP/drift_shift.txt" \
    > "$TMP/drift_table.txt"; then
  echo "trace_report drift missed the shifted window"; exit 1
fi
cat "$TMP/drift_table.txt"

echo "== refresh-loop smoke (2 cycles, poisoned canary) =="
# Bounded closed-loop pass: bootstrap + one POISONED refresh under live
# traffic. Nonzero exit on a stranded future, an SLO breach, a missed
# rollback, or a lost fault (report['ok'] covers the whole contract).
LIGHTGBM_TPU_WATCH_REFRESH_P99_MS="${LIGHTGBM_TPU_WATCH_REFRESH_P99_MS:-5000}" \
python - <<'EOF'
import tempfile

import numpy as np

from lightgbm_tpu.loop import RefreshController

kF = 10


def data_fn(cycle):
    rng = np.random.default_rng(70 + cycle)
    X = rng.normal(size=(800, kF))
    return X, (X[:, 0] + 0.5 * X[:, 1] > 0.2).astype(np.float64)


params = {"objective": "binary", "num_leaves": 7, "max_bin": 31,
          "verbosity": -1, "min_data_in_leaf": 10,
          "bin_construct_sample_cnt": 800}
with tempfile.TemporaryDirectory(prefix="lgbm_tpu_refresh_") as work:
    ctl = RefreshController(params, data_fn, num_features=kF,
                            work_dir=work, base_rounds=2,
                            extra_rounds=1, traffic_threads=2,
                            traffic_rows=32, drain_timeout_s=15)
    rep = ctl.run(cycles=2)
assert rep["ok"], "refresh loop violated its contract: %s" \
    % rep["problems"]
assert rep["refresh_rollbacks"] == rep["expected_rollbacks"] == 1
assert rep["stranded_futures"] == 0
assert rep["refresh_slo_breaches"] == 0
print("refresh loop ok (%d cycles, %.1fs/refresh, p99 %.1f ms, "
      "%d rollback, 0 stranded)"
      % (rep["num_cycles"], rep["refresh_cycle_seconds"],
         rep["serve_p99_during_refresh_ms"], rep["refresh_rollbacks"]))
EOF

echo "CHECK OK"
