#!/usr/bin/env python
"""Chrome-trace toolbox for lightgbm_tpu span traces: validate, merge,
summarize, tail.

Stdlib-only on purpose — it must load in <100 ms from CI and never drag
jax into a trace-processing subprocess.

Every PATH argument may be a single Chrome-trace JSON file OR a
streaming segment DIRECTORY produced by
``LIGHTGBM_TPU_TRACE_STREAM=dir`` (``segment-r<rank>-<seq>.json``
files, each a complete self-contained trace file — see
``lightgbm_tpu/obs/trace.py``). Compact binary segments
(``LIGHTGBM_TPU_TRACE_FORMAT=compact`` → ``.ctrace`` files, see
``lightgbm_tpu/obs/trace_compact.py``) load transparently everywhere
a JSON segment does — the codec module is stdlib-pure and loaded by
file path, so the no-jax guarantee holds.

Subcommands::

    trace_report.py validate trace.json|segdir/
        Schema + span-nesting check (complete events properly nested
        per (pid, tid) lane, ids resolvable, timestamps sane). For a
        segment directory: each segment validates standalone (parent
        links may cross segments), plus combined span-id-uniqueness
        and cross-segment nesting checks; reports total dropped
        events. Exit 0 when valid, 1 with one error per line otherwise.

    trace_report.py merge -o merged.json rank0.json rank1seg/ ...
        Interleave per-rank inputs by wall clock into ONE
        Perfetto-loadable file. A segment directory counts as one
        input PER RANK found inside it (segments of one rank
        concatenate — they never pid-collide with each other). Each
        input keeps (or, on collision, is remapped to) a distinct pid,
        so ranks render as separate process lanes. Prints the
        aggregate stage table of the merged trace to stdout.

    trace_report.py summary trace.json|segdir/ [more ...]
        Aggregate spans into the same stage table BENCH phases consume:
        {"phases": {stage: {seconds, calls, p50_ms, p99_ms}}}.

    trace_report.py tail segdir/ [--follow] [--interval S] [--spans]
        Live digest of a streaming run: one line per finalized segment
        (events, spans, wall-clock window, top stages); ``--follow``
        keeps polling for newly finalized segments until interrupted,
        ``--spans`` prints every span of each new segment instead of
        the digest.

    trace_report.py convert -o out.json seg.ctrace|segdir/|trace.json
        Lossless conversion to Chrome-trace JSON: a compact segment
        (or a directory mixing formats) comes out span-for-span equal
        to what the JSON writer would have produced.

    trace_report.py drift metrics.txt|http://gateway:port
        Per-feature drift table from the quality plane's metric
        families (``lightgbm_tpu_quality_*`` — see
        lightgbm_tpu/obs/quality.py): PSI and Jensen-Shannon per
        feature, prediction-score / label drift, edge-bin mass, window
        size, and any fired drift watchdog rules. ``--threshold``
        moves the PSI flagging cut (default 0.25), ``--json`` emits
        the raw report instead of the table. Exit 1 when any feature
        breaches the threshold (scriptable drift check).

    trace_report.py fleet segdir/ metrics.txt|http://gateway:port
        Run-correlated fleet report: joins a trace-segment directory
        with a gateway metrics dump (a file, or a live gateway URL to
        scrape) into one JSON report — per-rank stage tables from
        both sources, rank skew, push staleness, watchdog breach
        counters, and whether the trace run_id matches the metrics
        run_id.

The traces come from ``LIGHTGBM_TPU_TRACE=path.json`` /
``LIGHTGBM_TPU_TRACE_STREAM=dir`` (see docs/OBSERVABILITY.md);
multi-process dtrain writes one file per rank (``path.rankN.json``) or
rank-tagged segments into one shared directory.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

# spans may be emitted from perf_counter-anchored clocks; allow this
# much boundary slop (microseconds) before calling nesting broken
kNestEpsUs = 5.0

kKnownPhases = {"X", "i", "C", "M", "b", "e", "n"}

# compact binary segments: the codec (and the OpenMetrics parser the
# fleet report needs) are stdlib-pure modules inside the package,
# loaded BY FILE PATH so this tool never imports lightgbm_tpu itself
# (whose __init__ drags jax in)
kCompactMagicPrefix = b"LGTPUCT"
kCompactExt = ".ctrace"
_OBS_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        os.pardir, "lightgbm_tpu", "obs")
_side_modules: Dict[str, object] = {}


def _load_side_module(name: str):
    """Import ``lightgbm_tpu/obs/<name>.py`` standalone (no package)."""
    mod = _side_modules.get(name)
    if mod is None:
        import importlib.util
        path = os.path.join(_OBS_DIR, name + ".py")
        if not os.path.isfile(path):
            raise RuntimeError(
                "%s not found next to trace_report.py (expected %s)"
                % (name, path))
        spec = importlib.util.spec_from_file_location(
            "trace_report__" + name, path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _side_modules[name] = mod
    return mod


def _codec():
    return _load_side_module("trace_compact")


def _openmetrics():
    return _load_side_module("openmetrics")


def segment_files(dirpath: str) -> List[str]:
    """Finalized segment files of a streaming trace directory (JSON
    and compact alike), in rotation order (the seq number is
    zero-padded and precedes the extension, so lexical order is
    per-rank rotation order even in a mixed-format directory)."""
    return sorted(glob.glob(os.path.join(dirpath, "segment-*.json"))
                  + glob.glob(os.path.join(dirpath,
                                           "segment-*" + kCompactExt)))


def load_file(path: str) -> dict:
    """Load ONE trace file — Chrome-trace JSON (bare-array form
    normalized) or a compact binary segment (decoded to the identical
    document shape)."""
    with open(path, "rb") as f:
        head = f.read(len(kCompactMagicPrefix))
    if path.endswith(kCompactExt) or head == kCompactMagicPrefix:
        return _codec().read_segment(path)
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, list):
        doc = {"traceEvents": doc}
    if not isinstance(doc, dict):
        raise ValueError("%s: not a Chrome-trace JSON object" % path)
    return doc


def _concat_docs(docs: List[dict], other: dict) -> dict:
    evs: List[dict] = []
    seen_meta = set()
    for doc in docs:
        for e in doc.get("traceEvents", []):
            if isinstance(e, dict) and e.get("ph") == "M":
                # every segment repeats the lane metadata; keep one
                key = (e.get("name"), e.get("pid"), e.get("tid"),
                       json.dumps(e.get("args"), sort_keys=True))
                if key in seen_meta:
                    continue
                seen_meta.add(key)
            evs.append(e)
    return {"traceEvents": evs, "displayTimeUnit": "ms",
            "otherData": other}


def load_dir(dirpath: str) -> dict:
    """Combine a segment directory into one logical trace doc
    (segments concatenate in rotation order; lane metadata dedupes).
    ``otherData`` carries the per-segment records plus the MAX
    dropped-event counter seen (the spool's counter is cumulative)."""
    files = segment_files(dirpath)
    if not files:
        raise ValueError("%s: no segment-*.{json,ctrace} files" % dirpath)
    docs = [load_file(f) for f in files]
    segs = [dict(d.get("otherData") or {}, source_file=f)
            for d, f in zip(docs, files)]
    dropped = max((int(s.get("dropped_events", 0)) for s in segs),
                  default=0)
    return _concat_docs(docs, {"segment_dir": dirpath, "segments": segs,
                               "dropped_events": dropped})


def load_trace(path: str) -> dict:
    """Load a Chrome-trace file, or a whole segment directory as one
    combined doc."""
    if os.path.isdir(path):
        return load_dir(path)
    return load_file(path)


def _spans(doc: dict) -> List[dict]:
    return [e for e in doc.get("traceEvents", [])
            if isinstance(e, dict) and e.get("ph") == "X"]


def validate_trace(doc: dict, check_parents: bool = True) -> List[str]:
    """Return a list of schema/nesting errors (empty = valid).
    ``check_parents=False`` skips parent-link resolution — a single
    SEGMENT of a streaming trace is standalone-valid even though its
    spans may parent into an earlier segment (the combined-directory
    pass re-checks links across all segments)."""
    errors: List[str] = []
    evs = doc.get("traceEvents")
    if not isinstance(evs, list):
        return ["traceEvents is not a list"]
    if not evs:
        return ["traceEvents is empty"]
    span_ids = set()
    for i, e in enumerate(evs):
        if not isinstance(e, dict):
            errors.append("event %d: not an object" % i)
            continue
        ph = e.get("ph")
        if ph not in kKnownPhases:
            errors.append("event %d: unknown ph %r" % (i, ph))
            continue
        if ph == "M":
            continue
        if "pid" not in e or "tid" not in e:
            errors.append("event %d (%s): missing pid/tid"
                          % (i, e.get("name")))
        if ph in ("X", "i", "C"):
            ts = e.get("ts")
            if not isinstance(ts, (int, float)):
                errors.append("event %d (%s): bad ts %r"
                              % (i, e.get("name"), ts))
        if ph == "X":
            if not isinstance(e.get("name"), str) or not e.get("name"):
                errors.append("event %d: span without a name" % i)
            dur = e.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                errors.append("event %d (%s): bad dur %r"
                              % (i, e.get("name"), dur))
            args = e.get("args") or {}
            sid = args.get("span_id")
            if sid is not None:
                # span ids are unique per trace_id (merged multi-rank
                # files legitimately repeat ids across ranks)
                key = (args.get("trace_id"), sid)
                if key in span_ids:
                    errors.append("duplicate span_id %r in trace %r"
                                  % (sid, args.get("trace_id")))
                span_ids.add(key)
    if errors:
        return errors
    if check_parents:
        # parent links resolve within the same trace_id's span set
        for e in _spans(doc):
            args = e.get("args") or {}
            parent = args.get("parent_span_id")
            if parent not in (None, 0) \
                    and (args.get("trace_id"), parent) not in span_ids:
                errors.append("span %r (%s): parent_span_id %r unknown"
                              % (args.get("span_id"), e.get("name"),
                                 parent))
    errors.extend(_check_nesting(doc))
    return errors


def validate_dir(dirpath: str) -> Tuple[List[str], dict]:
    """Validate a streaming segment directory: every segment must be
    standalone-valid (parent links excepted — they may cross
    segments), then the combined doc re-checks span-id uniqueness and
    nesting across segments, and parent resolution when the spool
    dropped nothing (dropped chunks legitimately take parents with
    them). Returns (errors, stats)."""
    files = segment_files(dirpath)
    if not files:
        return (["%s: no segment-*.{json,ctrace} files" % dirpath], {})
    errors: List[str] = []
    for f in files:
        try:
            doc = load_file(f)
        except (OSError, ValueError) as e:
            errors.append("%s: %s" % (os.path.basename(f), e))
            continue
        for err in validate_trace(doc, check_parents=False):
            errors.append("%s: %s" % (os.path.basename(f), err))
    if errors:
        return errors, {}
    combined = load_dir(dirpath)
    dropped = int(combined["otherData"].get("dropped_events", 0))
    errors.extend(validate_trace(combined, check_parents=dropped == 0))
    spans = _spans(combined)
    stats = {"segments": len(files),
             "events": len(combined["traceEvents"]),
             "spans": len(spans),
             "stages": len({e["name"] for e in spans}),
             "dropped_events": dropped}
    return errors, stats


def _check_nesting(doc: dict) -> List[str]:
    """Spans on one (pid, tid) lane must be properly nested or
    disjoint — monotone nesting, no partial overlap."""
    errors: List[str] = []
    lanes: Dict[Tuple, List[dict]] = {}
    for e in _spans(doc):
        lanes.setdefault((e.get("pid"), e.get("tid")), []).append(e)
    for lane, spans in lanes.items():
        spans.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack: List[dict] = []  # enclosing spans, innermost last
        for e in spans:
            t0, t1 = e["ts"], e["ts"] + e["dur"]
            while stack and t0 >= stack[-1]["ts"] + stack[-1]["dur"] \
                    - kNestEpsUs:
                stack.pop()
            if stack:
                p0 = stack[-1]["ts"]
                p1 = p0 + stack[-1]["dur"]
                if t1 > p1 + kNestEpsUs or t0 < p0 - kNestEpsUs:
                    errors.append(
                        "lane %r: span %r [%0.1f, %0.1f] partially "
                        "overlaps %r [%0.1f, %0.1f]"
                        % (lane, e.get("name"), t0, t1,
                           stack[-1].get("name"), p0, p1))
                    continue
            stack.append(e)
    return errors


def span_tree(doc: dict) -> Dict:
    """Reconstruct the span forest from parent_span_id links:
    {span_id: {"name", "parent", "children": [span_id...]}}."""
    nodes: Dict = {}
    for e in _spans(doc):
        args = e.get("args") or {}
        sid = args.get("span_id")
        if sid is None:
            continue
        nodes[sid] = {"name": e.get("name"),
                      "ts": e.get("ts"), "dur": e.get("dur"),
                      "parent": args.get("parent_span_id") or 0,
                      "children": []}
    for sid, n in nodes.items():
        p = n["parent"]
        if p and p in nodes:
            nodes[p]["children"].append(sid)
    return nodes


def _merge_inputs(paths: List[str]) -> List[Tuple[str, dict]]:
    """Expand CLI paths into (label, doc) merge inputs: a file is one
    input; a segment directory becomes one input PER RANK found inside
    it (one rank's segments concatenate — they share a pid on purpose
    and must not be remapped apart)."""
    inputs: List[Tuple[str, dict]] = []
    for path in paths:
        if not os.path.isdir(path):
            inputs.append((path, load_file(path)))
            continue
        files = segment_files(path)
        if not files:
            raise ValueError("%s: no segment-*.{json,ctrace} files" % path)
        by_rank: Dict[object, List[dict]] = {}
        order: List[object] = []
        for f in files:
            doc = load_file(f)
            rank = (doc.get("otherData") or {}).get("process_index")
            if rank is None:
                pids = {e.get("pid") for e in doc.get("traceEvents", [])
                        if isinstance(e, dict) and "pid" in e}
                rank = min(pids) if pids else 0
            if rank not in by_rank:
                order.append(rank)
            by_rank.setdefault(rank, []).append(doc)
        for rank in order:
            label = "%s[rank%s]" % (path, rank)
            inputs.append((label, _concat_docs(
                by_rank[rank],
                {"segment_dir": path, "process_index": rank})))
    return inputs


def merge_traces(paths: List[str]) -> dict:
    """Combine per-rank trace files / segment directories: distinct
    process lanes (pids remapped on collision), events interleaved by
    wall-clock ts."""
    merged: List[dict] = []
    other: List[dict] = []
    used_pids = set()
    for path, doc in _merge_inputs(paths):
        file_pids = sorted({e.get("pid") for e in doc["traceEvents"]
                            if isinstance(e, dict) and "pid" in e},
                           key=lambda p: (p is None, p))
        remap = {}
        for pid in file_pids:
            new = pid if isinstance(pid, int) else 0
            while new in used_pids:
                new += 1
            remap[pid] = new
            used_pids.add(new)
        named = {e.get("pid") for e in doc["traceEvents"]
                 if isinstance(e, dict) and e.get("ph") == "M"
                 and e.get("name") == "process_name"}
        for e in doc["traceEvents"]:
            if not isinstance(e, dict):
                continue
            e = dict(e)
            if "pid" in e:
                e["pid"] = remap.get(e["pid"], e["pid"])
            merged.append(e)
        for old, new in remap.items():
            if old not in named:
                merged.append({"name": "process_name", "ph": "M",
                               "pid": new, "tid": 0,
                               "args": {"name": "rank %s (%s)"
                                        % (new, path)}})
        od = doc.get("otherData")
        if od:
            other.append(dict(od, source_file=path))
    meta = [e for e in merged if e.get("ph") == "M"]
    rest = sorted((e for e in merged if e.get("ph") != "M"),
                  key=lambda e: e.get("ts", 0.0))
    return {"traceEvents": meta + rest,
            "displayTimeUnit": "ms",
            "otherData": {"merged_from": paths, "ranks": other}}


def summarize(doc: dict) -> dict:
    """Aggregate spans into the BENCH-shaped stage table, plus a
    roofline section built from the compile spans' cost_analysis args
    (obs/compile.py captures FLOPs + bytes-accessed per jitted
    function): bytes_per_flop places each program on the roofline —
    high ratios are bandwidth-bound, which is the direct way to SEE the
    quantized histogram path moving fewer bytes than the exact one."""
    per_stage: Dict[str, List[float]] = {}
    roofline: Dict[str, dict] = {}
    for e in _spans(doc):
        per_stage.setdefault(e["name"], []).append(e["dur"] / 1e6)
        args = e.get("args") or {}
        if e.get("cat") == "compile" and "flops" in args:
            fn = args.get("fn", e["name"])
            r = roofline.setdefault(
                fn, {"flops": 0.0, "bytes_accessed": 0.0, "compiles": 0})
            r["flops"] += float(args.get("flops", 0.0))
            r["bytes_accessed"] += float(args.get("bytes_accessed", 0.0))
            r["compiles"] += 1
    for fn, r in roofline.items():
        r["bytes_per_flop"] = (round(r["bytes_accessed"] / r["flops"], 6)
                               if r["flops"] > 0 else None)
    phases = {}
    for name, durs in sorted(per_stage.items()):
        sv = sorted(durs)
        phases[name] = {
            "seconds": round(sum(durs), 6),
            "calls": len(durs),
            "p50_ms": round(_percentile(sv, 50) * 1e3, 3),
            "p99_ms": round(_percentile(sv, 99) * 1e3, 3),
        }
    out = {"phases": phases}
    if roofline:
        out["roofline"] = dict(sorted(roofline.items()))
    return out


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    k = (len(sorted_vals) - 1) * (q / 100.0)
    f = int(k)
    c = min(f + 1, len(sorted_vals) - 1)
    return sorted_vals[f] + (sorted_vals[c] - sorted_vals[f]) * (k - f)


def segment_digest(path: str, doc: dict, top: int = 3) -> str:
    """One tail line per finalized segment: size, wall-clock window,
    heaviest stages."""
    spans = _spans(doc)
    per: Dict[str, float] = {}
    for e in spans:
        per[e["name"]] = per.get(e["name"], 0.0) + e["dur"] / 1e6
    heavy = ", ".join("%s %.3fs" % (n, s) for n, s in
                      sorted(per.items(), key=lambda kv: -kv[1])[:top])
    ts = [e.get("ts") for e in doc.get("traceEvents", [])
          if isinstance(e, dict) and isinstance(e.get("ts"), (int, float))]
    window = ("%.3fs" % ((max(ts) - min(ts)) / 1e6)) if ts else "0s"
    od = doc.get("otherData") or {}
    return ("%s: %d events, %d spans, window %s, dropped %d%s"
            % (os.path.basename(path), len(doc.get("traceEvents", [])),
               len(spans), window, int(od.get("dropped_events", 0)),
               (" | " + heavy) if heavy else ""))


def fetch_metrics_text(src: str) -> str:
    """The OpenMetrics document for the ``fleet`` report: a dump file,
    or a live gateway scraped over HTTP (``/metrics`` appended when the
    URL has no path)."""
    if "://" in src:
        import urllib.parse
        import urllib.request
        if urllib.parse.urlsplit(src).path in ("", "/"):
            src = src.rstrip("/") + "/metrics"
        with urllib.request.urlopen(src, timeout=10) as resp:
            return resp.read().decode("utf-8", errors="replace")
    with open(src) as f:
        return f.read()


def fleet_report(tracedir: str, metrics_text: str,
                 metrics_source: str = "") -> dict:
    """Join a trace-segment directory with a gateway metrics dump into
    one run-correlated report: per-rank stage tables from BOTH sources,
    rank skew, push staleness, watchdog breach counters, and whether
    the trace's run_id matches the gateway's."""
    om = _openmetrics()
    parsed = om.parse_openmetrics(metrics_text)
    pfx = om.kPrefix

    # -- metrics side: per-rank stage seconds, push ages, breaches ------
    m_stage: Dict[str, Dict[str, float]] = {}
    push_age: Dict[str, float] = {}
    breaches: Dict[str, float] = {}
    m_run_ids = set()
    for (name, labels), v in sorted(parsed.items()):
        ld = dict(labels)
        if name == pfx + "stage_seconds_total":
            per = m_stage.setdefault(str(ld.get("rank", "?")), {})
            stage = str(ld.get("stage", "?"))
            per[stage] = round(per.get(stage, 0.0) + v, 6)
        elif name == pfx + "gateway_push_age_seconds":
            push_age["%s/%s" % (ld.get("rank", "?"),
                                ld.get("process", "?"))] = v
        elif name == pfx + "run_info" and ld.get("run_id"):
            m_run_ids.add(ld["run_id"])
        elif (name.startswith(pfx + "health_")
              and name.endswith("_total") and v > 0):
            rule = name[len(pfx + "health_"):-len("_total")]
            breaches[rule] = breaches.get(rule, 0.0) + v

    # -- trace side: per-rank span tables + segment run ids -------------
    by_rank: Dict[str, List[dict]] = {}
    t_run_ids = set()
    for f in segment_files(tracedir):
        doc = load_file(f)
        od = doc.get("otherData") or {}
        if od.get("run_id"):
            t_run_ids.add(str(od["run_id"]))
        rank = od.get("process_index")
        by_rank.setdefault(str(0 if rank is None else rank),
                           []).append(doc)
    t_stage = {rank: summarize(_concat_docs(docs, {}))["phases"]
               for rank, docs in sorted(by_rank.items())}

    # -- join ------------------------------------------------------------
    ranks = {}
    for rank in sorted(set(t_stage) | set(m_stage)):
        trace_s = round(sum(p["seconds"]
                            for p in t_stage.get(rank, {}).values()), 6)
        metric_s = round(sum(m_stage.get(rank, {}).values()), 6)
        ages = [a for k, a in push_age.items()
                if k.split("/", 1)[0] == rank]
        ranks[rank] = {
            "trace_stage_seconds": t_stage.get(rank, {}),
            "metrics_stage_seconds": m_stage.get(rank, {}),
            "trace_seconds": trace_s,
            "metrics_seconds": metric_s,
            "push_age_s": min(ages) if ages else None,
        }
    totals = [(r, e["metrics_seconds"] or e["trace_seconds"])
              for r, e in ranks.items()]
    busy = [t for _r, t in totals if t > 0]
    skew = {"ranks": len(totals)}
    if len(busy) >= 2:
        skew["slowest"] = round(max(busy), 6)
        skew["fastest"] = round(min(busy), 6)
        skew["ratio"] = round(max(busy) / min(busy), 3)
    match = (sorted(t_run_ids & m_run_ids)
             if t_run_ids and m_run_ids else [])
    return {
        "trace": {"dir": tracedir, "run_ids": sorted(t_run_ids),
                  "segments": len(segment_files(tracedir))},
        "metrics": {"source": metrics_source,
                    "run_ids": sorted(m_run_ids),
                    "push_age_s": push_age},
        "ranks": ranks,
        "rank_skew": skew,
        "breaches": breaches,
        "run_id_match": (bool(match) if t_run_ids and m_run_ids
                         else None),
        "run_ids_matched": match,
    }


kDriftRules = ("feature_drift", "prediction_drift", "label_drift",
               "retrain_required")


def drift_report(metrics_text: str, threshold: float = 0.25) -> dict:
    """Per-feature drift report from an OpenMetrics dump: the
    ``lightgbm_tpu_quality_*`` families the serve-path drift monitor
    exports each window, joined with the drift watchdog breach
    counters. ``features`` maps raw feature index -> {psi, js,
    breach}."""
    om = _openmetrics()
    parsed = om.parse_openmetrics(metrics_text)
    pfx = om.kPrefix
    qpfx = pfx + "quality_"
    features: Dict[str, dict] = {}
    summary: Dict[str, float] = {}
    breaches: Dict[str, float] = {}
    for (name, labels), v in sorted(parsed.items()):
        ld = dict(labels)
        if name == qpfx + "psi" and "feature" in ld:
            features.setdefault(str(ld["feature"]), {})["psi"] = v
        elif name == qpfx + "js" and "feature" in ld:
            features.setdefault(str(ld["feature"]), {})["js"] = v
        elif name.startswith(qpfx):
            key = name[len(qpfx):]
            if key.endswith("_total"):
                key = key[:-len("_total")]
            if not ld:
                summary[key] = v
        elif (name.startswith(pfx + "health_")
              and name.endswith("_total") and v > 0):
            rule = name[len(pfx + "health_"):-len("_total")]
            if rule in kDriftRules:
                breaches[rule] = v
    for f in features.values():
        f["breach"] = f.get("psi", 0.0) >= threshold
    return {
        "threshold": threshold,
        "features": dict(sorted(features.items(),
                                key=lambda kv: -kv[1].get("psi", 0.0))),
        "summary": summary,
        "watchdog_breaches": breaches,
        "drifted": sorted((k for k, f in features.items()
                           if f["breach"]),
                          key=lambda k: -features[k].get("psi", 0.0)),
    }


def render_drift(report: dict, out=None) -> None:
    """Human-readable form of :func:`drift_report`: a summary line, the
    per-feature table (worst PSI first), and any fired drift rules."""
    out = out or sys.stdout
    s = report["summary"]
    print("quality window: rows=%d windows=%d psi_max=%.4f "
          "js_max=%.4f score_psi=%s label_psi=%s edge_mass=%.4f"
          % (int(s.get("window_rows", 0)), int(s.get("windows", 0)),
             s.get("psi_max", 0.0), s.get("js_max", 0.0),
             ("%.4f" % s["score_psi"]) if "score_psi" in s else "n/a",
             ("%.4f" % s["label_psi"]) if "label_psi" in s else "n/a",
             s.get("edge_mass", 0.0)), file=out)
    feats = report["features"]
    if not feats:
        print("no per-feature quality gauges in this dump (quality "
              "plane inactive, or no window drained yet)", file=out)
    else:
        print("%8s %10s %10s  drift(PSI>=%.2f)"
              % ("feature", "psi", "js", report["threshold"]), file=out)
        for k, f in feats.items():
            print("%8s %10.4f %10.4f  %s"
                  % (k, f.get("psi", 0.0), f.get("js", 0.0),
                     "BREACH" if f["breach"] else "-"), file=out)
    for rule, count in sorted(report["watchdog_breaches"].items()):
        print("watchdog %s fired: %d" % (rule, int(count)), file=out)


def tail_dir(dirpath: str, follow: bool = False, interval: float = 2.0,
             print_spans: bool = False, out=None) -> int:
    """Print a digest (or every span) of each segment as it finalizes.
    One pass by default; ``--follow`` polls until interrupted."""
    out = out or sys.stdout
    seen: set = set()
    while True:
        for f in segment_files(dirpath):
            if f in seen:
                continue
            seen.add(f)
            try:
                doc = load_file(f)
            except (OSError, ValueError) as e:
                print("%s: UNREADABLE (%s)" % (os.path.basename(f), e),
                      file=out)
                continue
            if print_spans:
                for e in _spans(doc):
                    print("%s %.3f %8.3fms %s"
                          % (os.path.basename(f), e["ts"] / 1e6,
                             e["dur"] / 1e3, e["name"]), file=out)
            else:
                print(segment_digest(f, doc), file=out)
        out.flush()
        if not follow:
            return 0
        try:
            time.sleep(interval)
        except KeyboardInterrupt:
            return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="trace_report.py",
        description="validate / merge / summarize / tail lightgbm_tpu "
                    "Chrome-trace files and segment directories")
    sub = ap.add_subparsers(dest="cmd", required=True)
    ap_v = sub.add_parser("validate", help="schema + nesting check")
    ap_v.add_argument("path")
    ap_m = sub.add_parser("merge", help="merge per-rank traces")
    ap_m.add_argument("-o", "--output", required=True)
    ap_m.add_argument("paths", nargs="+")
    ap_s = sub.add_parser("summary", help="aggregate stage table")
    ap_s.add_argument("paths", nargs="+")
    ap_t = sub.add_parser("tail",
                          help="digest segments of a live streaming run")
    ap_t.add_argument("dir")
    ap_t.add_argument("--follow", action="store_true",
                      help="keep polling for new segments")
    ap_t.add_argument("--interval", type=float, default=2.0)
    ap_t.add_argument("--spans", action="store_true",
                      help="print every span instead of per-segment "
                           "digests")
    ap_c = sub.add_parser("convert",
                          help="lossless convert (compact segments "
                               "included) to Chrome-trace JSON")
    ap_c.add_argument("-o", "--output", required=True)
    ap_c.add_argument("path")
    ap_f = sub.add_parser("fleet",
                          help="run-correlated trace + gateway-metrics "
                               "fleet report")
    ap_f.add_argument("tracedir")
    ap_f.add_argument("metrics",
                      help="gateway metrics dump file, or gateway URL "
                           "to scrape")
    ap_d = sub.add_parser("drift",
                          help="per-feature drift table from the "
                               "quality plane's metric families")
    ap_d.add_argument("metrics",
                      help="gateway metrics dump file, or gateway URL "
                           "to scrape")
    ap_d.add_argument("--threshold", type=float, default=0.25,
                      help="PSI at or above this flags a feature as "
                           "drifted (default 0.25)")
    ap_d.add_argument("--json", action="store_true",
                      help="emit the raw report as JSON instead of "
                           "the table")
    args = ap.parse_args(argv)

    if args.cmd == "validate":
        if os.path.isdir(args.path):
            errors, stats = validate_dir(args.path)
            if errors:
                for err in errors:
                    print("INVALID: %s" % err, file=sys.stderr)
                return 1
            print("OK: %(segments)d segments, %(events)d events, "
                  "%(spans)d spans, %(stages)d stages, "
                  "%(dropped_events)d dropped" % stats)
            return 0
        try:
            doc = load_trace(args.path)
        except (OSError, ValueError) as e:
            print("INVALID: %s" % e, file=sys.stderr)
            return 1
        errors = validate_trace(doc)
        if errors:
            for err in errors:
                print("INVALID: %s" % err, file=sys.stderr)
            return 1
        spans = _spans(doc)
        print("OK: %d events, %d spans, %d stages"
              % (len(doc["traceEvents"]), len(spans),
                 len({e["name"] for e in spans})))
        return 0

    if args.cmd == "tail":
        if not os.path.isdir(args.dir):
            print("tail: %s is not a directory" % args.dir,
                  file=sys.stderr)
            return 2
        return tail_dir(args.dir, follow=args.follow,
                        interval=args.interval, print_spans=args.spans)

    if args.cmd == "merge":
        merged = merge_traces(args.paths)
        with open(args.output, "w") as f:
            json.dump(merged, f)
        print(json.dumps(summarize(merged), indent=2))
        return 0

    if args.cmd == "summary":
        if len(args.paths) == 1:
            doc = load_trace(args.paths[0])
        else:
            doc = merge_traces(args.paths)
        print(json.dumps(summarize(doc), indent=2))
        return 0

    if args.cmd == "convert":
        try:
            doc = load_trace(args.path)
        except (OSError, ValueError) as e:
            print("convert: %s" % e, file=sys.stderr)
            return 1
        with open(args.output, "w") as f:
            json.dump(doc, f)
        print("converted %s -> %s (%d events)"
              % (args.path, args.output, len(doc.get("traceEvents", []))))
        return 0

    if args.cmd == "drift":
        try:
            text = fetch_metrics_text(args.metrics)
            report = drift_report(text, threshold=args.threshold)
        except (OSError, ValueError) as e:
            print("drift: %s" % e, file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps(report, indent=2))
        else:
            render_drift(report)
        return 1 if report["drifted"] else 0

    if args.cmd == "fleet":
        if not os.path.isdir(args.tracedir):
            print("fleet: %s is not a directory" % args.tracedir,
                  file=sys.stderr)
            return 2
        try:
            text = fetch_metrics_text(args.metrics)
            report = fleet_report(args.tracedir, text,
                                  metrics_source=args.metrics)
        except (OSError, ValueError) as e:
            print("fleet: %s" % e, file=sys.stderr)
            return 1
        print(json.dumps(report, indent=2))
        return 0

    return 2


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # `trace_report.py summary ... | head` closing the pipe early
        # is not an error
        sys.exit(0)
