#!/usr/bin/env python
"""Chrome-trace toolbox for lightgbm_tpu span traces: validate, merge,
summarize.

Stdlib-only on purpose — it must load in <100 ms from CI and never drag
jax into a trace-processing subprocess.

Subcommands::

    trace_report.py validate trace.json
        Schema + span-nesting check (complete events properly nested
        per (pid, tid) lane, ids resolvable, timestamps sane).
        Exit 0 when valid, 1 with one error per line otherwise.

    trace_report.py merge -o merged.json rank0.json rank1.json ...
        Interleave per-rank trace files by wall clock into ONE
        Perfetto-loadable file. Each input keeps (or, on collision, is
        remapped to) a distinct pid, so ranks render as separate
        process lanes. Prints the aggregate stage table of the merged
        trace to stdout.

    trace_report.py summary trace.json [more.json ...]
        Aggregate spans into the same stage table BENCH phases consume:
        {"phases": {stage: {seconds, calls, p50_ms, p99_ms}}}.

The traces come from ``LIGHTGBM_TPU_TRACE=path.json`` (see
docs/OBSERVABILITY.md); multi-process dtrain writes one file per rank
(``path.rankN.json``).
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Tuple

# spans may be emitted from perf_counter-anchored clocks; allow this
# much boundary slop (microseconds) before calling nesting broken
kNestEpsUs = 5.0

kKnownPhases = {"X", "i", "C", "M", "b", "e", "n"}


def load_trace(path: str) -> dict:
    """Load a Chrome-trace file; normalizes the bare-array form to the
    object form."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, list):
        doc = {"traceEvents": doc}
    if not isinstance(doc, dict):
        raise ValueError("%s: not a Chrome-trace JSON object" % path)
    return doc


def _spans(doc: dict) -> List[dict]:
    return [e for e in doc.get("traceEvents", [])
            if isinstance(e, dict) and e.get("ph") == "X"]


def validate_trace(doc: dict) -> List[str]:
    """Return a list of schema/nesting errors (empty = valid)."""
    errors: List[str] = []
    evs = doc.get("traceEvents")
    if not isinstance(evs, list):
        return ["traceEvents is not a list"]
    if not evs:
        return ["traceEvents is empty"]
    span_ids = set()
    for i, e in enumerate(evs):
        if not isinstance(e, dict):
            errors.append("event %d: not an object" % i)
            continue
        ph = e.get("ph")
        if ph not in kKnownPhases:
            errors.append("event %d: unknown ph %r" % (i, ph))
            continue
        if ph == "M":
            continue
        if "pid" not in e or "tid" not in e:
            errors.append("event %d (%s): missing pid/tid"
                          % (i, e.get("name")))
        if ph in ("X", "i", "C"):
            ts = e.get("ts")
            if not isinstance(ts, (int, float)):
                errors.append("event %d (%s): bad ts %r"
                              % (i, e.get("name"), ts))
        if ph == "X":
            if not isinstance(e.get("name"), str) or not e.get("name"):
                errors.append("event %d: span without a name" % i)
            dur = e.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                errors.append("event %d (%s): bad dur %r"
                              % (i, e.get("name"), dur))
            args = e.get("args") or {}
            sid = args.get("span_id")
            if sid is not None:
                # span ids are unique per trace_id (merged multi-rank
                # files legitimately repeat ids across ranks)
                key = (args.get("trace_id"), sid)
                if key in span_ids:
                    errors.append("duplicate span_id %r in trace %r"
                                  % (sid, args.get("trace_id")))
                span_ids.add(key)
    if errors:
        return errors
    # parent links resolve within the same trace_id's span set
    for e in _spans(doc):
        args = e.get("args") or {}
        parent = args.get("parent_span_id")
        if parent not in (None, 0) \
                and (args.get("trace_id"), parent) not in span_ids:
            errors.append("span %r (%s): parent_span_id %r unknown"
                          % (args.get("span_id"), e.get("name"), parent))
    errors.extend(_check_nesting(doc))
    return errors


def _check_nesting(doc: dict) -> List[str]:
    """Spans on one (pid, tid) lane must be properly nested or
    disjoint — monotone nesting, no partial overlap."""
    errors: List[str] = []
    lanes: Dict[Tuple, List[dict]] = {}
    for e in _spans(doc):
        lanes.setdefault((e.get("pid"), e.get("tid")), []).append(e)
    for lane, spans in lanes.items():
        spans.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack: List[dict] = []  # enclosing spans, innermost last
        for e in spans:
            t0, t1 = e["ts"], e["ts"] + e["dur"]
            while stack and t0 >= stack[-1]["ts"] + stack[-1]["dur"] \
                    - kNestEpsUs:
                stack.pop()
            if stack:
                p0 = stack[-1]["ts"]
                p1 = p0 + stack[-1]["dur"]
                if t1 > p1 + kNestEpsUs or t0 < p0 - kNestEpsUs:
                    errors.append(
                        "lane %r: span %r [%0.1f, %0.1f] partially "
                        "overlaps %r [%0.1f, %0.1f]"
                        % (lane, e.get("name"), t0, t1,
                           stack[-1].get("name"), p0, p1))
                    continue
            stack.append(e)
    return errors


def span_tree(doc: dict) -> Dict:
    """Reconstruct the span forest from parent_span_id links:
    {span_id: {"name", "parent", "children": [span_id...]}}."""
    nodes: Dict = {}
    for e in _spans(doc):
        args = e.get("args") or {}
        sid = args.get("span_id")
        if sid is None:
            continue
        nodes[sid] = {"name": e.get("name"),
                      "ts": e.get("ts"), "dur": e.get("dur"),
                      "parent": args.get("parent_span_id") or 0,
                      "children": []}
    for sid, n in nodes.items():
        p = n["parent"]
        if p and p in nodes:
            nodes[p]["children"].append(sid)
    return nodes


def merge_traces(paths: List[str]) -> dict:
    """Combine per-rank trace files: distinct process lanes (pids
    remapped on collision), events interleaved by wall-clock ts."""
    merged: List[dict] = []
    other: List[dict] = []
    used_pids = set()
    for path in paths:
        doc = load_trace(path)
        file_pids = sorted({e.get("pid") for e in doc["traceEvents"]
                            if isinstance(e, dict) and "pid" in e},
                           key=lambda p: (p is None, p))
        remap = {}
        for pid in file_pids:
            new = pid if isinstance(pid, int) else 0
            while new in used_pids:
                new += 1
            remap[pid] = new
            used_pids.add(new)
        named = {e.get("pid") for e in doc["traceEvents"]
                 if isinstance(e, dict) and e.get("ph") == "M"
                 and e.get("name") == "process_name"}
        for e in doc["traceEvents"]:
            if not isinstance(e, dict):
                continue
            e = dict(e)
            if "pid" in e:
                e["pid"] = remap.get(e["pid"], e["pid"])
            merged.append(e)
        for old, new in remap.items():
            if old not in named:
                merged.append({"name": "process_name", "ph": "M",
                               "pid": new, "tid": 0,
                               "args": {"name": "rank %s (%s)"
                                        % (new, path)}})
        od = doc.get("otherData")
        if od:
            other.append(dict(od, source_file=path))
    meta = [e for e in merged if e.get("ph") == "M"]
    rest = sorted((e for e in merged if e.get("ph") != "M"),
                  key=lambda e: e.get("ts", 0.0))
    return {"traceEvents": meta + rest,
            "displayTimeUnit": "ms",
            "otherData": {"merged_from": paths, "ranks": other}}


def summarize(doc: dict) -> dict:
    """Aggregate spans into the BENCH-shaped stage table, plus a
    roofline section built from the compile spans' cost_analysis args
    (obs/compile.py captures FLOPs + bytes-accessed per jitted
    function): bytes_per_flop places each program on the roofline —
    high ratios are bandwidth-bound, which is the direct way to SEE the
    quantized histogram path moving fewer bytes than the exact one."""
    per_stage: Dict[str, List[float]] = {}
    roofline: Dict[str, dict] = {}
    for e in _spans(doc):
        per_stage.setdefault(e["name"], []).append(e["dur"] / 1e6)
        args = e.get("args") or {}
        if e.get("cat") == "compile" and "flops" in args:
            fn = args.get("fn", e["name"])
            r = roofline.setdefault(
                fn, {"flops": 0.0, "bytes_accessed": 0.0, "compiles": 0})
            r["flops"] += float(args.get("flops", 0.0))
            r["bytes_accessed"] += float(args.get("bytes_accessed", 0.0))
            r["compiles"] += 1
    for fn, r in roofline.items():
        r["bytes_per_flop"] = (round(r["bytes_accessed"] / r["flops"], 6)
                               if r["flops"] > 0 else None)
    phases = {}
    for name, durs in sorted(per_stage.items()):
        sv = sorted(durs)
        phases[name] = {
            "seconds": round(sum(durs), 6),
            "calls": len(durs),
            "p50_ms": round(_percentile(sv, 50) * 1e3, 3),
            "p99_ms": round(_percentile(sv, 99) * 1e3, 3),
        }
    out = {"phases": phases}
    if roofline:
        out["roofline"] = dict(sorted(roofline.items()))
    return out


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    k = (len(sorted_vals) - 1) * (q / 100.0)
    f = int(k)
    c = min(f + 1, len(sorted_vals) - 1)
    return sorted_vals[f] + (sorted_vals[c] - sorted_vals[f]) * (k - f)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="trace_report.py",
        description="validate / merge / summarize lightgbm_tpu "
                    "Chrome-trace files")
    sub = ap.add_subparsers(dest="cmd", required=True)
    ap_v = sub.add_parser("validate", help="schema + nesting check")
    ap_v.add_argument("path")
    ap_m = sub.add_parser("merge", help="merge per-rank traces")
    ap_m.add_argument("-o", "--output", required=True)
    ap_m.add_argument("paths", nargs="+")
    ap_s = sub.add_parser("summary", help="aggregate stage table")
    ap_s.add_argument("paths", nargs="+")
    args = ap.parse_args(argv)

    if args.cmd == "validate":
        try:
            doc = load_trace(args.path)
        except (OSError, ValueError) as e:
            print("INVALID: %s" % e, file=sys.stderr)
            return 1
        errors = validate_trace(doc)
        if errors:
            for err in errors:
                print("INVALID: %s" % err, file=sys.stderr)
            return 1
        spans = _spans(doc)
        print("OK: %d events, %d spans, %d stages"
              % (len(doc["traceEvents"]), len(spans),
                 len({e["name"] for e in spans})))
        return 0

    if args.cmd == "merge":
        merged = merge_traces(args.paths)
        with open(args.output, "w") as f:
            json.dump(merged, f)
        print(json.dumps(summarize(merged), indent=2))
        return 0

    if args.cmd == "summary":
        if len(args.paths) == 1:
            doc = load_trace(args.paths[0])
        else:
            doc = merge_traces(args.paths)
        print(json.dumps(summarize(doc), indent=2))
        return 0

    return 2


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # `trace_report.py summary ... | head` closing the pipe early
        # is not an error
        sys.exit(0)
