#!/bin/bash
# Run python with the TPU-tunnel plugin env scrubbed and an 8-device
# virtual CPU mesh — for one-off scripts/tests. The axon PJRT plugin
# (PALLAS_AXON_POOL_IPS + PYTHONPATH=/root/.axon_site) can wedge ANY
# jax init in-process when the tunnel is flaky, even under
# JAX_PLATFORMS=cpu; scrubbing before interpreter start is the only
# safe way (same trick as tests/conftest.py and
# __graft_entry__.scrubbed_cpu_env).
unset PALLAS_AXON_POOL_IPS PALLAS_AXON_REMOTE_COMPILE AXON_LOOPBACK_RELAY \
      PALLAS_AXON_TPU_GEN
export PYTHONPATH="$(echo "$PYTHONPATH" | tr ':' '\n' | \
                     grep -v axon_site | paste -sd:)"
export JAX_PLATFORMS=cpu JAX_PLATFORM_NAME=cpu
case "$XLA_FLAGS" in
  *xla_force_host_platform_device_count*) ;;
  *) export XLA_FLAGS="$XLA_FLAGS --xla_force_host_platform_device_count=8" ;;
esac
exec python "$@"
