#!/bin/bash
# Build the reference LightGBM CLI from the read-only tree at
# /root/reference into a /tmp scratch area, for the model-interchange /
# accuracy-parity tests (tests/test_reference_parity.py).
#
# The image's reference checkout has empty external_libs/ submodules and
# zero egress, so two tiny stand-in headers are generated (strtod-based
# fast_double_parser, snprintf-based fmt covering the three format
# strings LightGBM uses) and linear_tree_learner (needs Eigen) is
# stubbed to fail loudly if requested.
#
# Usage: tools/build_reference_parity_binary.sh [/root/reference]
# On success prints the binary path; export it as
#   LGBM_TPU_REFERENCE_BIN=<path> python -m pytest tests/test_reference_parity.py
set -euo pipefail

SRC=${1:-/root/reference}
WORK=/tmp/refsrc
BUILD=/tmp/refbuild

if [ -x "$WORK/lightgbm" ]; then
  echo "$WORK/lightgbm"
  exit 0
fi

rm -rf "$WORK" "$BUILD"
cp -r "$SRC" "$WORK"
chmod -R u+w "$WORK"

mkdir -p "$WORK/external_libs/fast_double_parser/include" \
         "$WORK/external_libs/fmt/include/fmt"

cat > "$WORK/external_libs/fast_double_parser/include/fast_double_parser.h" <<'EOF'
#pragma once
#include <cstdlib>
namespace fast_double_parser {
inline const char* parse_number(const char* p, double* out) {
  char* end = nullptr;
  double v = std::strtod(p, &end);
  if (end == p) return nullptr;
  *out = v;
  return end;
}
}  // namespace fast_double_parser
EOF

cat > "$WORK/external_libs/fmt/include/fmt/format.h" <<'EOF'
#pragma once
#include <cstdio>
#include <cstring>
#include <type_traits>
namespace fmt {
struct format_to_n_result { size_t size; };
namespace detail {
template <typename T>
inline int write_value(char* buf, size_t n, const char*, T value,
                       std::true_type) {
  if (std::is_signed<T>::value)
    return std::snprintf(buf, n, "%lld", static_cast<long long>(value));
  return std::snprintf(buf, n, "%llu",
                       static_cast<unsigned long long>(value));
}
template <typename T>
inline int write_value(char* buf, size_t n, const char* spec, T value,
                       std::false_type) {
  double v = static_cast<double>(value);
  if (std::strcmp(spec, "{:g}") == 0)
    return std::snprintf(buf, n, "%g", v);
  return std::snprintf(buf, n, "%.17g", v);
}
}  // namespace detail
template <typename T>
inline format_to_n_result format_to_n(char* buf, size_t n,
                                      const char* spec, T value) {
  int w = detail::write_value(
      buf, n, spec, value,
      std::integral_constant<bool, std::is_integral<T>::value>{});
  return format_to_n_result{w < 0 ? n : static_cast<size_t>(w)};
}
}  // namespace fmt
EOF

python3 - "$WORK" <<'EOF'
import sys
work = sys.argv[1]
p = work + "/src/treelearner/linear_tree_learner.cpp"
open(p, "w").write('''// Parity-build stub: Eigen submodule unavailable; linear_tree fails
// loudly if requested.
#include "linear_tree_learner.h"
#include <LightGBM/utils/log.h>
namespace LightGBM {
#define LGBM_STUB Log::Fatal("linear_tree unavailable in parity build")
void LinearTreeLearner::Init(const Dataset* d, bool h) {
  SerialTreeLearner::Init(d, h); LGBM_STUB; }
void LinearTreeLearner::InitLinear(const Dataset*, const int) { LGBM_STUB; }
Tree* LinearTreeLearner::Train(const score_t*, const score_t*, bool) {
  LGBM_STUB; return nullptr; }
void LinearTreeLearner::GetLeafMap(Tree*) const { LGBM_STUB; }
template <bool HAS_NAN>
void LinearTreeLearner::CalculateLinear(Tree*, bool, const score_t*,
                                        const score_t*, bool) const {
  LGBM_STUB; }
template void LinearTreeLearner::CalculateLinear<true>(
    Tree*, bool, const score_t*, const score_t*, bool) const;
template void LinearTreeLearner::CalculateLinear<false>(
    Tree*, bool, const score_t*, const score_t*, bool) const;
Tree* LinearTreeLearner::FitByExistingTree(const Tree*, const score_t*,
                                           const score_t*) const {
  LGBM_STUB; return nullptr; }
Tree* LinearTreeLearner::FitByExistingTree(
    const Tree*, const std::vector<int>&, const score_t*,
    const score_t*) const { LGBM_STUB; return nullptr; }
}  // namespace LightGBM
''')
EOF

mkdir -p "$BUILD"
cd "$BUILD"
cmake "$WORK" -DCMAKE_BUILD_TYPE=Release -DUSE_OPENMP=ON > cmake.log 2>&1
make -j"$(nproc)" lightgbm > make.log 2>&1
echo "$WORK/lightgbm"
