"""JLT009 — cross-module static-argument call sites.

JLT004 is binding-local by design: it flags a mutable literal reaching
a static position only when the ``instrument_jit(...,
static_argnums=...)`` binding and the call live in the SAME file. But
the package's jitted entry points are module-level bindings called
from everywhere (``ops.histogram._pallas_histogram`` is invoked from
the tree learners), so the obvious cross-module mistake —

    # ops/histogram.py
    _hist = instrument_jit("h", _body, static_argnums=(2,))
    # treelearner/somewhere.py
    from ..ops.histogram import _hist
    _hist(bins, gh, [16, 16])       # unhashable at a static position

— sailed through. This rule closes it with the project index: every
module-level name bound from a jit-maker call with a literal static
spec is registered project-wide; every call THROUGH such a name (in
any module) checks its static positions.

Flagged at a static position:

- a mutable literal or comprehension (unhashable — ``TypeError`` at
  call time), exactly JLT004's class;
- a literal-fresh constructor call (``list(...)``/``dict(...)``/
  ``set(...)``) — same unhashable crash, built one call later;
- a tuple literal containing either of the above (hashable never, or
  a retrace bomb if someone "fixes" the element type per call site).

Same-file calls stay JLT004's (one finding per site, one owner per
gap). Resolution is the project index's: suffix-matched module names,
no instance-attribute indirection.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, Optional, Set, Tuple

from ..engine import FileContext, Finding
from . import Rule
from .jlt004_static_args import _MUTABLE, _static_spec

_FRESH_CTORS = ("list", "dict", "set")


def _fresh_unhashable(node: ast.AST) -> Optional[str]:
    """Why this expression can never be a sound static argument, or
    None when it is (or might be) fine."""
    if isinstance(node, _MUTABLE):
        return "mutable %s literal" % type(node).__name__.lower()
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id in _FRESH_CTORS:
        return "fresh %s(...) built at the call" % node.func.id
    if isinstance(node, ast.Tuple):
        for el in node.elts:
            why = _fresh_unhashable(el)
            if why:
                return "tuple containing a " + why
    return None


def _bindings(project) -> Dict[str, Tuple[Set[int], Set[str]]]:
    """Project-wide registry: "module:name" of every module-level jit
    binding with a literal static spec -> (static nums, static names)."""
    cached = project.cache.get("jlt009")
    if cached is not None:
        return cached
    out: Dict[str, Tuple[Set[int], Set[str]]] = {}
    for key, assign in project.module_assigns.items():
        if not isinstance(assign.value, ast.Call):
            continue
        mod = key.split(":", 1)[0]
        ctx = next((c for c in project.contexts if c.module == mod),
                   None)
        if ctx is None:
            continue
        spec = _static_spec(ctx, assign.value)
        if spec:
            out[key] = (spec[0], spec[1])
    project.cache["jlt009"] = out
    return out


class StaticCallSiteRule(Rule):
    id = "JLT009"
    name = "static-callsite"
    summary = ("unhashable/literal-fresh value reaching a static "
               "position of a jit binding defined in another module")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        project = ctx.project
        if project is None:
            return iter(())
        bindings = _bindings(project)
        if not bindings:
            return iter(())
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = project.resolve_assign(
                ctx, ctx.canonical(node.func))
            if resolved is None:
                continue
            mod, name, _assign = resolved
            spec = bindings.get(mod + ":" + name)
            if spec is None or mod == ctx.module:
                continue  # same-binding sites are JLT004's findings
            nums, names = spec
            for i, arg in enumerate(node.args):
                if i not in nums:
                    continue
                why = _fresh_unhashable(arg)
                if why:
                    out.append(self.finding(
                        ctx, arg,
                        "%s at static position %d of %s.%s (bound "
                        "with static_argnums in %s): unhashable at "
                        "call time, or a fresh compile per call — "
                        "pass a frozen tuple of few, stable values"
                        % (why, i, mod, name, mod)))
            for kw in node.keywords:
                if kw.arg not in names:
                    continue
                why = _fresh_unhashable(kw.value)
                if why:
                    out.append(self.finding(
                        ctx, kw.value,
                        "%s for static arg %r of %s.%s (bound with "
                        "static_argnames in %s): unhashable at call "
                        "time, or a fresh compile per call — pass a "
                        "frozen tuple of few, stable values"
                        % (why, kw.arg, mod, name, mod)))
        return iter(out)
