"""JLT001 — host-device synchronization in hot-path modules.

The bug class: training/serving hot loops that silently pull device
values to the host (or push host scalars to the device) — ``.item()``,
``float()/int()/bool()`` on a jax value, ``np.asarray`` of a jax value,
``jax.device_get``, ``.block_until_ready()``. Each one is a blocking
round-trip the device trace never shows; on a remote TPU a single stray
``.item()`` per split step serializes the whole pipeline (the exact
failure mode the GPU GBDT literature guards its kernels against).

Scope: every module except ``obs/`` (whose JOB is reading device state
off the hot path), ``serve/server.py`` (the host-facing front end) and
tests. Deliberate syncs — the per-batch split-record read-back, the
one-shot Pallas probe — carry ``# jaxlint: disable=JLT001 -- reason``
suppressions at the call site, which is exactly the point: every sync
in a hot-path module is either machine-checked out or visibly argued
for in-line.

Jax-ness of a conversion argument is decided by local taint: the
argument is itself a ``jax.*``/``jnp.*`` call, or a name assigned from
one earlier in the same scope (single-assignment tracking; attribute
reads like ``self.label`` are NOT tainted — one-time setup conversions
of stored arrays are normal). Cross-function flow is out of scope
(ROADMAP: deferred).
"""
from __future__ import annotations

import ast
from typing import Iterator, Set

from ..engine import FileContext, Finding
from . import Rule, iter_statements_ordered, shallow_walk, walk_scopes

_CONVERTERS = {"float", "int", "bool"}
_NP_CONVERTERS = {"numpy.asarray", "numpy.array"}
#: jax-rooted calls whose RESULT is a host value (device handles,
#: process topology, completed cross-process gathers) — converting
#: those is not a device sync, so they are not taint sources
_HOST_RESULTS = (
    "jax.device_get", "jax.devices", "jax.local_devices",
    "jax.device_count", "jax.local_device_count", "jax.process_count",
    "jax.process_index", "jax.default_backend",
    "jax.experimental.multihost_utils.process_allgather",
)


def _is_jax_call(ctx: FileContext, node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    canon = ctx.canonical(node.func)
    if not canon or not (canon == "jax" or canon.startswith("jax.")):
        return False
    host_tails = tuple("." + h.rsplit(".", 1)[-1] for h in _HOST_RESULTS)
    return not (canon in _HOST_RESULTS or canon.endswith(host_tails))


class HostSyncRule(Rule):
    id = "JLT001"
    name = "host-sync"
    summary = ("implicit host-device synchronization in a hot-path "
               "module")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if ctx.host_sync_exempt:
            return
        for scope in walk_scopes(ctx.tree):
            yield from self._check_scope(ctx, scope)

    def _check_scope(self, ctx, scope) -> Iterator[Finding]:
        tainted: Set[str] = set()
        # statement-granular ordering: assignments inside a with/loop/
        # if body must taint BEFORE later statements of the same block
        # are checked, while within ONE statement the checks run first
        # (in ``x = jnp.f(np.g(x))`` the RHS is judged against x's
        # previous binding)
        for stmt in iter_statements_ordered(scope.body):
            nodes = self._ordered(stmt)
            for node in nodes:
                yield from self._check_node(ctx, node, tainted)
            for node in nodes:
                self._update_taint(ctx, node, tainted)

    @staticmethod
    def _ordered(stmt):
        nodes = list(shallow_walk(stmt))
        nodes.sort(key=lambda n: (getattr(n, "lineno", 0),
                                  getattr(n, "col_offset", 0)))
        return nodes

    def _update_taint(self, ctx, node, tainted: Set[str]) -> None:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            return
        tgt = node.targets[0]
        if not isinstance(tgt, ast.Name):
            return
        value = node.value
        src_tainted = _is_jax_call(ctx, value)
        if isinstance(value, (ast.BinOp, ast.Subscript)):
            inner = (value.left if isinstance(value, ast.BinOp)
                     else value.value)
            if isinstance(inner, ast.Name) and inner.id in tainted:
                src_tainted = True
        if src_tainted:
            tainted.add(tgt.id)
        else:
            tainted.discard(tgt.id)

    def _check_node(self, ctx, node, tainted) -> Iterator[Finding]:
        if not isinstance(node, ast.Call):
            return
        func = node.func
        # unconditional syncs
        if isinstance(func, ast.Attribute):
            if func.attr == "item" and not node.args:
                yield self.finding(
                    ctx, node,
                    ".item() forces a device→host sync; read values "
                    "via jax.device_get at a deliberate sync point "
                    "(and suppress with a rationale)")
                return
            if func.attr == "block_until_ready":
                yield self.finding(
                    ctx, node,
                    ".block_until_ready() fences the dispatch "
                    "pipeline; only obs/ may fence (readiness "
                    "drainer) — move the wait or suppress with a "
                    "rationale")
                return
        canon = ctx.canonical(func)
        if canon == "jax.device_get":
            yield self.finding(
                ctx, node,
                "jax.device_get blocks on the device; a hot-path "
                "module may only sync at its documented per-batch "
                "read-back — suppress with a rationale if this IS "
                "that point")
            return
        # conversions of jax values
        name = (canon or "").split(".")[-1] if canon else ""
        is_converter = (isinstance(func, ast.Name)
                        and func.id in _CONVERTERS) \
            or (canon in _NP_CONVERTERS)
        if not is_converter or not node.args:
            return
        arg = node.args[0]
        arg_is_jax = _is_jax_call(ctx, arg) \
            or (isinstance(arg, ast.Name) and arg.id in tainted) \
            or (isinstance(arg, ast.Subscript)
                and isinstance(arg.value, ast.Name)
                and arg.value.id in tainted)
        if arg_is_jax:
            label = func.id if isinstance(func, ast.Name) else name
            yield self.finding(
                ctx, node,
                "%s() on a jax value synchronizes with the device; "
                "keep the computation on device or device_get at a "
                "deliberate sync point" % label)
