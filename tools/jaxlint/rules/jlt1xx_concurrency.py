"""JLT101/JLT102/JLT103 — the concurrency-discipline family.

The review-hardening record of PRs 10–15 shows the codebase's dominant
recurring bug class is threading discipline, not jax semantics: shed
accounting serializing the dispatch worker behind event-log file I/O
under the server lock (PR 10), iterate-while-mutating on the shared
bucket-policy dict across replica predictors (PR 11), per-model gauges
clobbered across servers. These rules encode those reviews as a gate
over the threaded modules (``engine.THREADED_MODULES``: ``serve/``,
``loop/``, ``obs/gateway.py``, ``obs/export.py``, ``io/shards.py``).

- **JLT101 unlocked-shared-mutation** — a method reachable from a
  thread target (``threading.Thread(target=self._run)``, an executor
  ``submit(self._stage)``) writes an instance attribute that
  non-worker methods also touch, without holding any of the class's
  designated locks (attributes bound from ``threading.Lock/RLock/
  Condition`` in the class). The PR 11 bucket-policy bug, as a rule.
- **JLT102 blocking-under-lock** — blocking work inside a ``with
  self._lock:`` block: ``events.emit``/``flush`` (file I/O on flush),
  ``log.*``, ``time.sleep``, ``open``/``urlopen``, thread ``join``,
  future ``result``, or a one-call-deep helper that does one of those.
  The PR 10 shed-accounting bug, as a rule. ``Condition.wait`` is
  exempt — waiting releases the lock by contract.
- **JLT103 lock-order-inversion** — two lock acquisitions observed in
  both orders anywhere in the project (directly nested ``with``
  blocks, or a call made while holding a lock into a function whose
  transitive closure acquires another). Lock identity is lexical:
  ``module.Class.attr`` for ``self`` locks, ``module.name`` for
  module-level locks — two code paths that nest the same PAIR of
  named locks in opposite orders deadlock the first time their
  threads interleave.

Known limits (docs/STATIC_ANALYSIS.md): aliasing a shared attribute
into a local (``st = self._stats[t]``) hides the write; instance-
attribute indirection (``self.registry.publish()``) does not resolve,
so cross-object cycles through composed objects are the runtime
sanitizer's job (utils/locktrace.py), not this rule's.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from ..engine import FileContext, Finding
from . import Rule

_LOCK_CTORS = {"Lock": "Lock", "RLock": "RLock",
               "Condition": "Condition"}
_SYNC_CTORS = ("Event", "Thread", "Timer", "Semaphore",
               "BoundedSemaphore", "Barrier", "ThreadPoolExecutor",
               "local", "finalize")
_MUTATORS = {"append", "extend", "insert", "remove", "pop", "popitem",
             "clear", "update", "setdefault", "add", "discard"}
_COND_METHODS = {"wait", "wait_for", "notify", "notify_all", "acquire",
                 "release", "set", "clear", "is_set", "locked"}
_LOG_FNS = {"debug", "info", "warning", "warning_always", "error",
            "fatal", "exception"}
_THREADISH = re.compile(r"thread|pool|proc|pusher|exporter|worker",
                        re.IGNORECASE)
_FUTUREISH = re.compile(r"fut|future", re.IGNORECASE)


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _threading_ctor(ctx, value: ast.AST) -> Optional[str]:
    """'Lock'/'RLock'/'Condition'/'sync' for a threading-object
    constructor call, else None."""
    if not isinstance(value, ast.Call):
        return None
    canon = ctx.canonical(value.func) or ""
    parts = canon.split(".")
    if len(parts) >= 2 and parts[0] in ("threading", "concurrent",
                                        "weakref"):
        tail = parts[-1]
        if tail in _LOCK_CTORS:
            return _LOCK_CTORS[tail]
        if tail in _SYNC_CTORS:
            return "sync"
    return None


class _ClassCx:
    """One class's concurrency shape: locks, worker roots, and every
    method's attribute traffic annotated with the locks held."""

    def __init__(self, ctx: FileContext, node: ast.ClassDef) -> None:
        self.ctx = ctx
        self.node = node
        self.name = node.name
        self.locks: Dict[str, str] = {}     # attr -> ctor kind
        self.sync_attrs: Set[str] = set()   # events/threads/pools
        self.methods: Dict[str, ast.FunctionDef] = {
            m.name: m for m in node.body
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))}
        self.worker_roots: Set[str] = set()
        self.calls: Dict[str, Set[str]] = {}
        #: every self-method call: (caller, callee, node, locks held)
        self.method_calls: List[Tuple[str, str, ast.Call,
                                      frozenset]] = []
        #: method -> [(attr, node, frozenset(locks held))]
        self.writes: Dict[str, List[Tuple[str, ast.AST,
                                          frozenset]]] = {}
        self.reads: Dict[str, Set[str]] = {}
        self.init_attrs: Set[str] = set()
        for m in self.methods.values():
            self._scan_method(m)

    # -- per-method scan ----------------------------------------------
    def _scan_method(self, m) -> None:
        self.calls[m.name] = set()
        self.writes[m.name] = []
        self.reads[m.name] = set()
        self._walk(m.name, m.body, frozenset())

    def _walk(self, mname: str, stmts: Sequence[ast.stmt],
              held: frozenset) -> None:
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                # nested defs (thread bodies defined inline) run on
                # their own schedule: scan them with NO lock context
                if isinstance(s, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                    self._walk(mname, s.body, frozenset())
                continue
            if isinstance(s, ast.With):
                got = set(held)
                for item in s.items:
                    attr = _self_attr(item.context_expr)
                    if attr is not None:
                        got.add(attr)
                self._scan_exprs(mname, s, held)  # the with items
                self._walk(mname, s.body, frozenset(got))
                continue
            self._scan_exprs(mname, s, held)
            for blk in (getattr(s, "body", None),
                        getattr(s, "orelse", None),
                        getattr(s, "finalbody", None)):
                if isinstance(blk, list) and blk \
                        and isinstance(blk[0], ast.stmt):
                    self._walk(mname, blk, held)
            for h in getattr(s, "handlers", []) or []:
                self._walk(mname, h.body, held)

    def _scan_exprs(self, mname: str, stmt: ast.stmt,
                    held: frozenset) -> None:
        todo = [stmt] if not isinstance(stmt, ast.With) \
            else [it.context_expr for it in stmt.items]
        seen: List[ast.AST] = []
        while todo:
            n = todo.pop()
            seen.append(n)
            for ch in ast.iter_child_nodes(n):
                if isinstance(ch, (ast.stmt, ast.FunctionDef,
                                   ast.AsyncFunctionDef, ast.ClassDef)):
                    continue
                todo.append(ch)
        for node in seen:
            if isinstance(node, ast.Attribute):
                attr = _self_attr(node)
                if attr and isinstance(node.ctx, ast.Load):
                    self.reads[mname].add(attr)
            elif isinstance(node, ast.Call):
                self._scan_call(mname, node, held)
        # writes: assignment/augassign targets on this statement
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            for tgt in targets:
                base = tgt
                while isinstance(base, (ast.Subscript, ast.Starred)):
                    base = base.value
                if isinstance(base, (ast.Tuple, ast.List)):
                    elts = base.elts
                else:
                    elts = [base]
                for el in elts:
                    while isinstance(el, (ast.Subscript, ast.Starred)):
                        el = el.value
                    attr = _self_attr(el)
                    if attr:
                        self.writes[mname].append((attr, tgt, held))
                        if mname == "__init__":
                            self.init_attrs.add(attr)
                            kind = _threading_ctor(
                                self.ctx, getattr(stmt, "value", None))
                            if kind in ("Lock", "RLock", "Condition"):
                                self.locks[attr] = kind
                            elif kind == "sync":
                                self.sync_attrs.add(attr)

    def _scan_call(self, mname: str, call: ast.Call,
                   held: frozenset) -> None:
        canon = self.ctx.canonical(call.func) or ""
        tail = canon.rsplit(".", 1)[-1]
        # worker roots: Thread(target=self.X) / pool.submit(self.X)
        if tail in ("Thread", "Timer"):
            for kw in call.keywords:
                if kw.arg == "target":
                    attr = _self_attr(kw.value)
                    if attr:
                        self.worker_roots.add(attr)
        elif isinstance(call.func, ast.Attribute) \
                and call.func.attr == "submit" and call.args:
            attr = _self_attr(call.args[0])
            if attr:
                self.worker_roots.add(attr)
        # self-method call graph
        if isinstance(call.func, ast.Attribute):
            attr = _self_attr(call.func)
            if attr and attr in self.methods:
                self.calls[mname].add(attr)
                self.method_calls.append((mname, attr, call, held))
            # mutating container method on a self attribute
            if call.func.attr in _MUTATORS:
                inner = call.func.value
                while isinstance(inner, ast.Subscript):
                    inner = inner.value
                tgt_attr = _self_attr(inner)
                if tgt_attr:
                    self.writes[mname].append(
                        (tgt_attr, call, held))

    # -- derived -------------------------------------------------------
    def worker_closure(self) -> Set[str]:
        out: Set[str] = set()
        todo = [r for r in self.worker_roots if r in self.methods]
        while todo:
            m = todo.pop()
            if m in out:
                continue
            out.add(m)
            todo.extend(c for c in self.calls.get(m, ())
                        if c not in out)
        return out


def _classes(ctx: FileContext) -> List[_ClassCx]:
    cached = getattr(ctx, "_jlt1xx_classes", None)
    if cached is None:
        cached = [_ClassCx(ctx, n) for n in ctx.tree.body
                  if isinstance(n, ast.ClassDef)]
        ctx._jlt1xx_classes = cached
    return cached


def _module_locks(ctx: FileContext) -> Dict[str, str]:
    """Module-level names bound to threading locks in this file."""
    out: Dict[str, str] = {}
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign):
            kind = _threading_ctor(ctx, node.value)
            if kind in ("Lock", "RLock", "Condition"):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out[tgt.id] = kind
    return out


# ----------------------------------------------------------------------
# JLT101
# ----------------------------------------------------------------------

class UnlockedSharedMutationRule(Rule):
    id = "JLT101"
    name = "unlocked-shared-mutation"
    summary = ("worker-thread method mutates a shared attribute "
               "without the class's designated lock held")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.is_threaded_module:
            return iter(())
        out: List[Finding] = []
        for cls in _classes(ctx):
            if not cls.locks:
                continue
            workers = cls.worker_closure()
            if not workers:
                continue
            outside = set(cls.methods) - workers - {"__init__"}
            shared: Set[str] = set()
            for m in outside:
                shared |= cls.reads.get(m, set())
                shared |= {a for a, _, _ in cls.writes.get(m, ())}
            lock_names = set(cls.locks)
            for m in sorted(workers):
                if m.endswith("_locked"):
                    # the repo convention: a *_locked method asserts
                    # its CALLER holds the lock — audited below
                    continue
                for attr, node, held in cls.writes.get(m, ()):
                    if attr in lock_names or attr in cls.sync_attrs:
                        continue
                    if attr not in cls.init_attrs \
                            or attr not in shared:
                        continue
                    if held & lock_names:
                        continue
                    out.append(self.finding(
                        ctx, node,
                        "%s.%s runs on a worker thread and mutates "
                        "self.%s — an attribute other methods touch — "
                        "without holding %s; unguarded read-modify-"
                        "write across threads loses updates"
                        % (cls.name, m, attr,
                           " or ".join("self." + n
                                       for n in sorted(lock_names)))))
            # the convention's other half: nobody may CALL a *_locked
            # method without a designated lock actually held
            for caller, callee, node, held in cls.method_calls:
                if not callee.endswith("_locked"):
                    continue
                if caller.endswith("_locked") or caller == "__init__":
                    continue
                if held & lock_names:
                    continue
                out.append(self.finding(
                    ctx, node,
                    "%s.%s calls self.%s() without holding %s — the "
                    "_locked suffix is a contract that the caller "
                    "already holds the class lock"
                    % (cls.name, caller, callee,
                       " or ".join("self." + n
                                   for n in sorted(lock_names)))))
        return iter(out)


# ----------------------------------------------------------------------
# JLT102
# ----------------------------------------------------------------------

def _direct_blocking(ctx, call: ast.Call) -> Optional[str]:
    """Why one call blocks, judged locally, or None."""
    canon = ctx.canonical(call.func) or ""
    parts = canon.split(".")
    tail = parts[-1]
    if tail in _COND_METHODS:
        return None  # Condition/Event protocol: wait releases the lock
    if canon == "open" or tail == "urlopen":
        return "file/network I/O (%s)" % tail
    if canon == "time.sleep":
        return "time.sleep"
    if len(parts) >= 2 and parts[-2] == "events" \
            and tail in ("emit", "flush"):
        return ("events.%s — the event sink flushes to disk, exactly "
                "the PR 10 shed-accounting serialization" % tail)
    if len(parts) >= 2 and parts[-2] == "log" and tail in _LOG_FNS:
        return "log.%s (stderr write under contention)" % tail
    if len(parts) >= 2 and parts[-2] == "faults" and tail == "check":
        # the chaos probe emits a FLUSHED fault_injected event when it
        # fires; recognized by name so a single-file scan classifies
        # the call identically to a project scan (where the transitive
        # summary of obs.faults.check would catch it anyway)
        return "faults.check (flushed fault-injection emit)"
    if tail == "retry_call":
        return "retry_call (sleeps between attempts)"
    if isinstance(call.func, ast.Attribute):
        recv = call.func.value
        recv_name = ""
        while isinstance(recv, (ast.Subscript,)):
            recv = recv.value
        if isinstance(recv, ast.Attribute):
            recv_name = recv.attr
        elif isinstance(recv, ast.Name):
            recv_name = recv.id
        if call.func.attr == "join" and _THREADISH.search(recv_name):
            return "thread join"
        if call.func.attr == "result" and _FUTUREISH.search(recv_name):
            return "future result wait"
        if call.func.attr == "shutdown" and _THREADISH.search(recv_name):
            return "executor shutdown"
    return None


def _blocking_summaries(project) -> Dict[str, str]:
    """fn.key -> blocking reason for functions whose body DIRECTLY
    blocks (one-call-deep transitivity for JLT102)."""
    cached = project.cache.get("jlt102")
    if cached is not None:
        return cached
    out: Dict[str, str] = {}
    for fi in project.functions.values():
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call):
                why = _direct_blocking(fi.ctx, node)
                if why:
                    out[fi.key] = why
                    break
    project.cache["jlt102"] = out
    return out


class BlockingUnderLockRule(Rule):
    id = "JLT102"
    name = "blocking-under-lock"
    summary = ("blocking I/O, event emit/flush, or logging inside a "
               "with-lock block")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.is_threaded_module:
            return iter(())
        out: List[Finding] = []
        mod_locks = _module_locks(ctx)
        lock_attrs = {attr for cls in _classes(ctx)
                      for attr in cls.locks}
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.With):
                continue
            lock_name = None
            for item in node.items:
                attr = _self_attr(item.context_expr)
                if attr is not None and attr in lock_attrs:
                    lock_name = "self." + attr
                elif isinstance(item.context_expr, ast.Name) \
                        and item.context_expr.id in mod_locks:
                    lock_name = item.context_expr.id
            if lock_name is None:
                continue
            self._scan_body(ctx, node.body, lock_name, out)
        return iter(out)

    def _scan_body(self, ctx, stmts, lock_name, out) -> None:
        cls_of: Dict[int, Optional[str]] = {}
        enclosing = self._enclosing_classes(ctx)
        for s in stmts:
            for node in ast.walk(s):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    continue
                if not isinstance(node, ast.Call):
                    continue
                why = _direct_blocking(ctx, node)
                if why is None and ctx.project is not None:
                    callee = ctx.project.resolve_call(
                        ctx, node, cls=enclosing.get(id(node)))
                    if callee is not None:
                        deep = _blocking_summaries(
                            ctx.project).get(callee.key)
                        if deep:
                            why = "a call to %s(), which does %s" \
                                % (callee.qualname, deep)
                if why:
                    out.append(self.finding(
                        ctx, node,
                        "blocking work inside 'with %s:': %s — every "
                        "other thread contending for the lock "
                        "serializes behind it; move it outside the "
                        "critical section (snapshot under the lock, "
                        "act after release)" % (lock_name, why)))

    def _enclosing_classes(self, ctx) -> Dict[int, Optional[str]]:
        cached = getattr(ctx, "_jlt102_cls_of", None)
        if cached is not None:
            return cached
        out: Dict[int, Optional[str]] = {}

        def walk(node, cls):
            for ch in ast.iter_child_nodes(node):
                if isinstance(ch, ast.ClassDef):
                    walk(ch, ch.name)
                else:
                    if isinstance(ch, ast.Call):
                        out[id(ch)] = cls
                    walk(ch, cls)
        walk(ctx.tree, None)
        ctx._jlt102_cls_of = out
        return out


# ----------------------------------------------------------------------
# JLT103
# ----------------------------------------------------------------------

def _lock_edges(project):
    """Project-wide lock-order graph: (lockA, lockB) -> witness
    (relpath, line, detail) for A held while acquiring B."""
    cached = project.cache.get("jlt103")
    if cached is not None:
        return cached
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    fns = [fi for fi in project.functions.values()
           if fi.ctx.is_threaded_module]
    mod_locks = {id(fi.ctx): _module_locks(fi.ctx) for fi in fns}
    cls_locks: Dict[Tuple[int, str], Set[str]] = {}
    for fi in fns:
        if fi.cls is not None \
                and (id(fi.ctx), fi.cls) not in cls_locks:
            for cls in _classes(fi.ctx):
                cls_locks[(id(fi.ctx), cls.name)] = set(cls.locks)

    def lock_id(fi, expr) -> Optional[str]:
        attr = _self_attr(expr)
        if attr is not None and fi.cls is not None \
                and attr in cls_locks.get((id(fi.ctx), fi.cls), ()):
            return "%s.%s.%s" % (fi.ctx.module, fi.cls, attr)
        if isinstance(expr, ast.Name) \
                and expr.id in mod_locks[id(fi.ctx)]:
            return "%s.%s" % (fi.ctx.module, expr.id)
        return None

    # pass 1: per-function direct acquisitions + resolved calls,
    # with the lock stack at each point
    direct: Dict[str, Set[str]] = {}
    call_sites: Dict[str, List[Tuple[Tuple[str, ...], object]]] = {}

    def walk(fi, stmts, held: Tuple[str, ...]):
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            now = held
            if isinstance(s, ast.With):
                for item in s.items:
                    lid = lock_id(fi, item.context_expr)
                    if lid is None:
                        continue
                    direct[fi.key].add(lid)
                    for h in now:
                        if h != lid:
                            edges.setdefault((h, lid), (
                                fi.ctx.relpath, item.context_expr.lineno,
                                "%s acquires %s while holding %s"
                                % (fi.qualname, lid, h)))
                    now = now + (lid,)
                walk(fi, s.body, now)
                continue
            for node in ast.walk(s):
                if isinstance(node, ast.Call):
                    callee = project.resolve_call(fi.ctx, node,
                                                  cls=fi.cls)
                    if callee is not None and held:
                        call_sites[fi.key].append(
                            (held, (callee.key, fi.ctx.relpath,
                                    node.lineno, fi.qualname)))
            for blk in (getattr(s, "body", None),
                        getattr(s, "orelse", None),
                        getattr(s, "finalbody", None)):
                if isinstance(blk, list) and blk \
                        and isinstance(blk[0], ast.stmt):
                    walk(fi, blk, held)
            for h in getattr(s, "handlers", []) or []:
                walk(fi, h.body, held)

    for fi in fns:
        direct[fi.key] = set()
        call_sites[fi.key] = []
        walk(fi, fi.node.body, ())

    # pass 2: transitive acquisition closure (bounded fixed point)
    closure: Dict[str, Set[str]] = {k: set(v) for k, v in direct.items()}
    for _ in range(6):
        changed = False
        for key, sites in call_sites.items():
            for _held, (ckey, _rp, _ln, _qn) in sites:
                got = closure.get(ckey)
                if got and not got <= closure[key]:
                    closure[key] |= got
                    changed = True
        if not changed:
            break

    # pass 3: call-mediated edges — holding H, calling into a closure
    # that acquires L
    for key, sites in call_sites.items():
        for held, (ckey, rp, ln, qn) in sites:
            for lid in closure.get(ckey, ()):
                for h in held:
                    if h != lid:
                        edges.setdefault((h, lid), (
                            rp, ln,
                            "%s calls %s while holding %s (callee "
                            "acquires %s)" % (qn, ckey.split(":")[-1],
                                              h, lid)))

    project.cache["jlt103"] = edges
    return edges


class LockOrderRule(Rule):
    id = "JLT103"
    name = "lock-order"
    summary = ("the same lock pair acquired in both orders on "
               "different code paths (deadlock on interleave)")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.is_threaded_module or ctx.project is None:
            return iter(())
        edges = _lock_edges(ctx.project)
        out: List[Finding] = []
        for (a, b), (rp, line, detail) in edges.items():
            if rp != ctx.relpath:
                continue
            rev = edges.get((b, a))
            if rev is None:
                continue
            out.append(Finding(
                self.id, ctx.path, line, 0,
                "lock order inversion: %s, but %s:%d takes %s before "
                "%s (%s) — two threads interleaving these paths "
                "deadlock; pick one order and hold to it"
                % (detail, rev[0], rev[1], b, a, rev[2])))
        return iter(out)
