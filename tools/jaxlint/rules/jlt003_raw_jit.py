"""JLT003 — raw ``jax.jit`` call sites.

``obs/compile.instrument_jit`` is the sanctioned owner of every jit
boundary: it counts traces, warns on retrace storms, captures
cost_analysis FLOPs/bytes into ``jit_trace`` events, and feeds the
roofline summary. A raw ``jax.jit`` site is a compile boundary the
observability layer cannot see — it was exactly how the objectives'
gradient compiles stayed invisible until PR 5 migrated them. This rule
is the enforcement arm of ``instrument_jit`` (docs/OBSERVABILITY.md).

Flags any reference to ``jax.jit`` (attribute access, ``from jax
import jit``, ``functools.partial(jax.jit, ...)`` — all reduce to the
same resolved name) outside ``obs/compile.py``.
"""
from __future__ import annotations

import ast
from typing import Iterator

from ..engine import FileContext, Finding
from . import Rule


class RawJitRule(Rule):
    id = "JLT003"
    name = "raw-jit"
    summary = "jax.jit call site bypassing obs/compile.instrument_jit"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if ctx.owns_jit or ctx.is_test:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.Attribute, ast.Name)):
                continue
            # only flag the outermost Attribute of a chain
            if isinstance(node, ast.Name) and ctx.canonical(node) \
                    == "jax.jit":
                yield self._hit(ctx, node)
            elif isinstance(node, ast.Attribute) \
                    and ctx.canonical(node) == "jax.jit":
                yield self._hit(ctx, node)

    def _hit(self, ctx, node) -> Finding:
        return self.finding(
            ctx, node,
            "raw jax.jit bypasses compile tracking — use "
            "obs/compile.instrument_jit(name, fn, **jit_kwargs) (or "
            "instrument_jit_method for static-self methods) so the "
            "compile shows up in jit_trace events and the roofline "
            "summary")
