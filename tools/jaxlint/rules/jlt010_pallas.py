"""JLT010 — Pallas kernel invariants.

The histogram megakernel (``ops/histogram.py:_hist_kernel_body``) and
every future Pallas kernel share a handful of invariants that fail
LATE when broken — at trace time on a TPU run, or worse, silently as
a wrong-dtype accumulation. This rule pins them statically:

- **grid/index-map arity**: every ``BlockSpec`` index-map lambda takes
  exactly ``len(grid)`` parameters, and an index map returning a
  literal tuple returns one index per block dimension;
- **spec/shape rank**: the ``out_specs`` block rank equals the
  ``out_shape`` ``ShapeDtypeStruct`` rank (a rank mismatch is a
  guaranteed Mosaic lowering error);
- **call arity**: ``pallas_call(...)(args)`` passes exactly
  ``len(in_specs)`` arrays, and a resolvable kernel function (a name
  or ``functools.partial(name, ...)``) has exactly
  ``in_specs + outputs`` ref parameters after the partial-bound ones;
- **accumulator dtype**: ``dot``/``dot_general``/``einsum``/``matmul``
  inside a kernel body must pass ``preferred_element_type`` — the
  default accumulates int8×int8 into int8 and bf16×bf16 into bf16,
  which is exactly the quantized-histogram overflow the f32/int32
  accumulator exists to prevent;
- **VMEM tile budget**: a module issuing ``pallas_call`` must carry a
  static budget guard (a ``*VMEM_BUDGET*`` constant or a ``*fits*``
  predicate, the ``_pallas_fits`` idiom) so tile sizes are checked
  against VMEM before dispatch, and literal ``PALLAS_ROW_TILE*``
  constants must be sublane-aligned (multiples of 8).

Kernel bodies are found two ways: resolved from a ``pallas_call``
first argument, or by name (``*kernel_body*`` — the repo convention).
Non-literal shapes/grids are skipped, never guessed.
"""
from __future__ import annotations

import ast
import re
from typing import Iterator, List, Optional, Set

from ..engine import FileContext, Finding
from . import Rule

_KERNEL_NAME = re.compile(r"kernel_body")
_ROW_TILE = re.compile(r"^PALLAS_ROW_TILE")
_BUDGET_NAME = re.compile(r"VMEM_BUDGET")
_FITS_NAME = re.compile(r"fits")
_DOT_OPS = ("dot", "dot_general", "einsum", "matmul")


def _uses_pallas(ctx: FileContext) -> bool:
    return any("pallas" in v for v in ctx._aliases.values())


def _is_pallas_call(ctx, node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    canon = ctx.canonical(node.func) or ""
    return canon.rsplit(".", 1)[-1] == "pallas_call"


def _kw(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _block_specs(node: Optional[ast.AST], ctx) -> List[ast.Call]:
    """The BlockSpec calls of an in_specs/out_specs expression (a bare
    spec, or a literal list/tuple of them)."""
    if node is None:
        return []
    elts = node.elts if isinstance(node, (ast.List, ast.Tuple)) \
        else [node]
    out = []
    for el in elts:
        if isinstance(el, ast.Call):
            canon = ctx.canonical(el.func) or ""
            if canon.rsplit(".", 1)[-1] == "BlockSpec":
                out.append(el)
    return out


def _spec_shape_rank(spec: ast.Call) -> Optional[int]:
    if spec.args and isinstance(spec.args[0], (ast.Tuple, ast.List)):
        return len(spec.args[0].elts)
    return None


def _spec_index_map(spec: ast.Call) -> Optional[ast.Lambda]:
    for cand in list(spec.args[1:2]) + [kw.value for kw in spec.keywords
                                        if kw.arg == "index_map"]:
        if isinstance(cand, ast.Lambda):
            return cand
    return None


class PallasInvariantsRule(Rule):
    id = "JLT010"
    name = "pallas-invariants"
    summary = ("Pallas BlockSpec/grid/kernel-arity mismatch, missing "
               "accumulator dtype, or missing VMEM budget guard")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _uses_pallas(ctx):
            return iter(())
        out: List[Finding] = []
        calls = [n for n in ast.walk(ctx.tree)
                 if _is_pallas_call(ctx, n)]
        invocations = {id(n.func): n for n in ast.walk(ctx.tree)
                       if isinstance(n, ast.Call)
                       and isinstance(n.func, ast.Call)}
        kernel_names: Set[str] = set()
        for call in calls:
            kernel_names |= self._check_call_site(
                ctx, call, invocations.get(id(call)), out)
        self._check_kernels(ctx, kernel_names, out)
        if calls:
            self._check_budget(ctx, calls[0], out)
        self._check_row_tiles(ctx, out)
        return iter(out)

    # -- one pallas_call site ------------------------------------------
    def _check_call_site(self, ctx, call: ast.Call,
                         invocation: Optional[ast.Call],
                         out) -> Set[str]:
        grid = _kw(call, "grid")
        grid_rank = len(grid.elts) if isinstance(
            grid, (ast.Tuple, ast.List)) else None
        in_specs = _block_specs(_kw(call, "in_specs"), ctx)
        out_specs = _block_specs(_kw(call, "out_specs"), ctx)
        for spec in in_specs + out_specs:
            rank = _spec_shape_rank(spec)
            lam = _spec_index_map(spec)
            if lam is None:
                continue
            n_lam = len(lam.args.args)
            if grid_rank is not None and n_lam != grid_rank:
                out.append(self.finding(
                    ctx, lam,
                    "BlockSpec index map takes %d parameter(s) but the "
                    "grid has %d dimension(s) — each grid axis feeds "
                    "one index-map argument" % (n_lam, grid_rank)))
            if rank is not None and isinstance(lam.body, ast.Tuple) \
                    and len(lam.body.elts) != rank:
                out.append(self.finding(
                    ctx, lam,
                    "BlockSpec index map returns %d block index(es) "
                    "for a %d-dimensional block shape — one index per "
                    "block dimension" % (len(lam.body.elts), rank)))
        # out_specs rank vs out_shape rank
        out_shape = _kw(call, "out_shape")
        if isinstance(out_shape, ast.Call) and out_shape.args \
                and isinstance(out_shape.args[0],
                               (ast.Tuple, ast.List)) \
                and len(out_specs) == 1:
            want = len(out_shape.args[0].elts)
            got = _spec_shape_rank(out_specs[0])
            if got is not None and got != want:
                out.append(self.finding(
                    ctx, out_specs[0],
                    "out_specs block is rank %d but out_shape is rank "
                    "%d — the output BlockSpec must match the output "
                    "array's rank" % (got, want)))
        # immediate invocation arity: pallas_call(...)(a, b)
        if invocation is not None and in_specs:
            n_args = len(invocation.args)
            if not any(isinstance(a, ast.Starred)
                       for a in invocation.args) \
                    and n_args != len(in_specs):
                out.append(self.finding(
                    ctx, invocation,
                    "pallas_call declares %d in_specs but is invoked "
                    "with %d array(s) — every operand needs exactly "
                    "one BlockSpec" % (len(in_specs), n_args)))
        # kernel arity (name or functools.partial(name, bound...))
        names: Set[str] = set()
        if call.args:
            k = call.args[0]
            bound = 0
            if isinstance(k, ast.Call):
                canon = ctx.canonical(k.func) or ""
                if canon.rsplit(".", 1)[-1] == "partial" and k.args \
                        and isinstance(k.args[0], ast.Name):
                    bound = len(k.args) - 1
                    k = k.args[0]
            if isinstance(k, ast.Name):
                names.add(k.id)
                fi = ctx.project.resolve_symbol(ctx, k.id) \
                    if ctx.project else None
                if fi is not None and in_specs:
                    n_out = 1 if len(out_specs) <= 1 else len(out_specs)
                    n_refs = len(fi.params) - bound
                    want = len(in_specs) + n_out
                    if n_refs != want:
                        out.append(self.finding(
                            ctx, call,
                            "kernel %s has %d ref parameter(s) after "
                            "%d partial-bound, but this pallas_call "
                            "supplies %d (in_specs=%d + outputs=%d)"
                            % (fi.qualname, n_refs, bound, want,
                               len(in_specs), n_out)))
        return names

    # -- kernel bodies -------------------------------------------------
    def _check_kernels(self, ctx, kernel_names: Set[str], out) -> None:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if node.name not in kernel_names \
                    and not _KERNEL_NAME.search(node.name):
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                canon = ctx.canonical(sub.func) or ""
                if canon.rsplit(".", 1)[-1] not in _DOT_OPS:
                    continue
                if not canon.startswith(("jax.", "jnp.", "jax")):
                    continue
                if _kw(sub, "preferred_element_type") is None:
                    out.append(self.finding(
                        ctx, sub,
                        "%s inside kernel %s without "
                        "preferred_element_type — the default "
                        "accumulates in the input dtype (int8*int8 "
                        "stays int8): pin the accumulator dtype "
                        "explicitly" % (canon.rsplit(".", 1)[-1],
                                        node.name)))

    # -- module VMEM discipline ----------------------------------------
    def _check_budget(self, ctx, first_call: ast.Call, out) -> None:
        has_budget = False
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) \
                            and _BUDGET_NAME.search(tgt.id):
                        has_budget = True
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)) \
                    and _FITS_NAME.search(node.name):
                has_budget = True
        if not has_budget:
            out.append(self.finding(
                ctx, first_call,
                "pallas_call with no static VMEM budget guard in the "
                "module — add a *_VMEM_BUDGET constant and a fits-"
                "style predicate (the _pallas_fits idiom) so tile "
                "sizes are bounded before dispatch, not by a Mosaic "
                "OOM at trace time"))

    def _check_row_tiles(self, ctx, out) -> None:
        for node in ctx.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if not (isinstance(tgt, ast.Name)
                        and _ROW_TILE.search(tgt.id)):
                    continue
                v = node.value
                if isinstance(v, ast.Constant) \
                        and isinstance(v.value, int) \
                        and (v.value <= 0 or v.value % 8):
                    out.append(self.finding(
                        ctx, node,
                        "%s = %d is not a positive multiple of 8 — "
                        "TPU sublane tiling pads row tiles to 8, so "
                        "a misaligned tile wastes VMEM the budget "
                        "arithmetic does not account for"
                        % (tgt.id, v.value)))
