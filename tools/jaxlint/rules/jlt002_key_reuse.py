"""JLT002 — PRNG key reuse.

The bug class behind the pre-PR-3 ``make_rand_bins`` padding
divergence: the same PRNG key flowing into two ``jax.random.*`` draws
(directly, or via a helper call) without an interleaving ``split`` /
``fold_in``. Jax keys are VALUES, not stateful generators — a reused
key re-produces the same stream, which in this codebase showed up as
serial/mesh learners drawing "random" thresholds that silently agreed
or diverged depending on padding.

Tracking is scope-local and branch-aware but deliberately simple
(cross-function key flow is a ROADMAP deferral):

- a name holds a key if it is a parameter named ``key``/``rng``/
  ``*_key``/``keys`` or is assigned from ``jax.random.PRNGKey`` /
  ``split`` / ``fold_in`` (tuple unpacking from ``split`` included);
  dotted stores like ``self._key`` participate too;
- deriving calls (``split``/``fold_in``/``PRNGKey``/``key_data``/
  ``clone``) do NOT consume; any other call a key is passed to DOES
  (a sampler, or a helper that presumably samples);
- reassignment from a deriver starts a fresh generation; consuming the
  same generation twice is the finding;
- ``if``/``else`` branches are analyzed independently and merged
  (exclusive branches may each consume once); loop bodies are walked
  twice so a consume-without-reassign inside a loop is caught.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Tuple

from ..engine import FileContext, Finding
from . import Rule

_KEY_PARAM = re.compile(r"(^|_)(key|rng|keys)$")
_DERIVERS = {"PRNGKey", "key", "split", "fold_in", "key_data",
             "wrap_key_data", "clone"}


def _key_expr_name(node: ast.AST) -> Optional[str]:
    """Dotted string for Name/Attribute chains (``key``, ``self._key``);
    None otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(parts[::-1])
    return None


class _State:
    __slots__ = ("gen", "used")

    def __init__(self):
        self.gen: Dict[str, int] = {}       # name -> generation
        self.used: Dict[str, Tuple[int, int]] = {}  # name -> (gen, line)

    def clone(self) -> "_State":
        s = _State()
        s.gen = dict(self.gen)
        s.used = dict(self.used)
        return s

    def merge(self, a: "_State", b: "_State") -> None:
        names = set(a.gen) | set(b.gen)
        self.gen = {}
        self.used = {}
        for n in names:
            ga, gb = a.gen.get(n, -1), b.gen.get(n, -1)
            self.gen[n] = max(ga, gb)
            ua, ub = a.used.get(n), b.used.get(n)
            # keep a consume only if it happened at the surviving
            # generation; exclusive-branch consumes merge to one
            for u in (ua, ub):
                if u is not None and u[0] == self.gen[n]:
                    self.used[n] = u


class KeyReuseRule(Rule):
    id = "JLT002"
    name = "key-reuse"
    summary = ("PRNG key consumed twice without an interleaving "
               "split/fold_in")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                state = _State()
                for arg in (list(node.args.posonlyargs)
                            + list(node.args.args)
                            + list(node.args.kwonlyargs)):
                    if _KEY_PARAM.search(arg.arg):
                        state.gen[arg.arg] = 0
                self._walk_block(ctx, node.body, state, out)
        return iter(out)

    # -- statement walking ---------------------------------------------
    def _walk_block(self, ctx, stmts, state: _State, out) -> None:
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            if isinstance(s, ast.If):
                a, b = state.clone(), state.clone()
                self._walk_block(ctx, s.body, a, out)
                self._walk_block(ctx, s.orelse, b, out)
                state.merge(a, b)
            elif isinstance(s, (ast.For, ast.AsyncFor, ast.While)):
                self._walk_block(ctx, s.body, state, out)
                self._walk_block(ctx, s.body, state, out)
                self._walk_block(ctx, s.orelse, state, out)
            elif isinstance(s, ast.With):
                self._walk_block(ctx, s.body, state, out)
            elif isinstance(s, ast.Try):
                self._walk_block(ctx, s.body, state, out)
                for h in s.handlers:
                    self._walk_block(ctx, h.body, state.clone(), out)
                self._walk_block(ctx, s.finalbody, state, out)
            else:
                self._process_stmt(ctx, s, state, out)

    # -- one simple statement ------------------------------------------
    def _process_stmt(self, ctx, stmt, state: _State, out) -> None:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                self._process_call(ctx, node, state, out)
        if isinstance(stmt, ast.Assign):
            self._process_assign(ctx, stmt, state)

    def _process_call(self, ctx, call, state: _State, out) -> None:
        canon = ctx.canonical(call.func) or ""
        if canon.startswith("jax.random.") \
                and canon.rsplit(".", 1)[-1] in _DERIVERS:
            return  # deriving a key never consumes it
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            name = _key_expr_name(arg)
            if name is None or name not in state.gen:
                continue
            gen = state.gen[name]
            prev = state.used.get(name)
            if prev is not None and prev[0] == gen:
                out.append(self.finding(
                    ctx, call,
                    "PRNG key %r already consumed at line %d with no "
                    "interleaving jax.random.split/fold_in — reusing "
                    "it replays the same random stream" %
                    (name, prev[1])))
            else:
                state.used[name] = (gen, call.lineno)

    def _process_assign(self, ctx, stmt, state: _State) -> None:
        value = stmt.value
        canon = ctx.canonical(value.func) or "" \
            if isinstance(value, ast.Call) else ""
        derives = (canon.startswith("jax.random.")
                   and canon.rsplit(".", 1)[-1] in _DERIVERS)
        for tgt in stmt.targets:
            elts = tgt.elts if isinstance(tgt, (ast.Tuple, ast.List)) \
                else [tgt]
            for el in elts:
                name = _key_expr_name(el)
                if name is None:
                    continue
                if derives or _KEY_PARAM.search(name.rsplit(".", 1)[-1]):
                    state.gen[name] = state.gen.get(name, -1) + 1
                    state.used.pop(name, None)
                elif name in state.gen:
                    # overwritten with a non-key value
                    del state.gen[name]
                    state.used.pop(name, None)
