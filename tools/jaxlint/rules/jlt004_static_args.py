"""JLT004 — unhashable or churn-prone static arguments.

``static_argnums``/``static_argnames`` make jax HASH the argument and
key the compile cache on it. A list/dict/set (or a comprehension)
reaching a static position either crashes (unhashable) or — wrapped in
a tuple by a well-meaning caller — becomes a retrace bomb: every
distinct value compiles a fresh executable. The learners thread their
static config through frozen tuples (``hist_impl``) for exactly this
reason.

Detection is binding-local: the rule records names bound (or
immediately called) from ``jax.jit(...)`` / ``instrument_jit(...)``
with literal ``static_argnums``/``static_argnames``, then flags calls
through those names that place a list/dict/set literal or comprehension
at a static position. Cross-module call tracking is a deferred ROADMAP
item — the gate this rule provides is "the obvious local mistake never
lands".
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..engine import FileContext, Finding
from . import Rule

_MUTABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
            ast.SetComp, ast.GeneratorExp)


def _is_jit_maker(ctx: FileContext, func: ast.AST) -> bool:
    canon = ctx.canonical(func) or ""
    return canon == "jax.jit" or canon.rsplit(".", 1)[-1] in (
        "instrument_jit", "instrument_jit_method")


def _literal_ints(node: ast.AST) -> Optional[Set[int]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[int] = set()
        for el in node.elts:
            if not (isinstance(el, ast.Constant)
                    and isinstance(el.value, int)):
                return None
            out.add(el.value)
        return out
    return None


def _literal_strs(node: ast.AST) -> Optional[Set[str]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[str] = set()
        for el in node.elts:
            if not (isinstance(el, ast.Constant)
                    and isinstance(el.value, str)):
                return None
            out.add(el.value)
        return out
    return None


def _static_spec(ctx, call: ast.Call
                 ) -> Optional[Tuple[Set[int], Set[str], int]]:
    """(static positions, static names, positional offset) of a
    jit-maker call, or None. instrument_jit's leading ``name`` argument
    does not shift anything: the wrapped function's own signature is
    what argnums index."""
    if not _is_jit_maker(ctx, call.func):
        return None
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            got = _literal_ints(kw.value)
            if got:
                nums |= got
        elif kw.arg == "static_argnames":
            got = _literal_strs(kw.value)
            if got:
                names |= got
    if not nums and not names:
        return None
    return nums, names, 0


class StaticArgsRule(Rule):
    id = "JLT004"
    name = "static-args"
    summary = ("list/dict literal reaching a static_argnums/"
               "static_argnames position (retrace bomb)")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        bindings: Dict[str, Tuple[Set[int], Set[str]]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.value, ast.Call):
                spec = _static_spec(ctx, node.value)
                if spec:
                    tgt = node.targets[0]
                    name = None
                    if isinstance(tgt, ast.Name):
                        name = tgt.id
                    elif isinstance(tgt, ast.Attribute) \
                            and isinstance(tgt.value, ast.Name):
                        name = tgt.value.id + "." + tgt.attr
                    if name:
                        bindings[name] = (spec[0], spec[1])
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Call):
                # immediate call: jax.jit(f, static_argnums=...)(args)
                spec = _static_spec(ctx, node.func)
                if spec:
                    yield from self._check_call(ctx, node, spec[0],
                                                spec[1])
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = None
            if isinstance(node.func, ast.Name):
                name = node.func.id
            elif isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name):
                name = node.func.value.id + "." + node.func.attr
            if name in bindings:
                nums, names = bindings[name]
                yield from self._check_call(ctx, node, nums, names)

    def _check_call(self, ctx, call: ast.Call, nums: Set[int],
                    names: Set[str]) -> Iterator[Finding]:
        for i, arg in enumerate(call.args):
            if i in nums and isinstance(arg, _MUTABLE):
                yield self.finding(
                    ctx, arg,
                    "mutable %s literal at static position %d: "
                    "unhashable (TypeError) — pass a frozen tuple, and "
                    "only if its values are few and stable (every "
                    "distinct static value compiles a new executable)"
                    % (type(arg).__name__.lower(), i))
        for kw in call.keywords:
            if kw.arg in names and isinstance(kw.value, _MUTABLE):
                yield self.finding(
                    ctx, kw.value,
                    "mutable %s literal for static arg %r: unhashable "
                    "(TypeError) — pass a frozen tuple of few, stable "
                    "values" % (type(kw.value).__name__.lower(), kw.arg))
