"""JLT008 — cross-function PRNG key flow.

The gap JLT002 admits by design: JLT002 only knows a name holds a key
when it is a key-named parameter or is assigned directly from
``jax.random.PRNGKey/split/fold_in``. A key that crosses a function
boundary — returned by a helper, or passed through one — is invisible
to it, so this replays a stream silently:

    def make_key(seed):
        return jax.random.PRNGKey(seed)

    def sample(seed):
        k = make_key(seed)
        a = jax.random.uniform(k)
        b = jax.random.normal(k)      # same stream as `a` — JLT008

This rule builds per-function summaries over the project call graph
(:mod:`tools.jaxlint.project`) and closes that gap:

- ``returns fresh key``: the function returns a value derived from
  ``jax.random.PRNGKey/split/fold_in`` (directly, via a key-returning
  local, or via another fresh-key-returning project function) — a name
  assigned from a call to it becomes a tracked key generation;
- ``passes through``: the function returns one of its own key-named
  parameters (possibly inside a tuple). At the call site the unpacked
  target ALIASES the argument: if the callee also consumes that
  parameter, the target is born already-consumed, so the first draw on
  it is a replay (``x, key2 = draw(key)`` then ``normal(key2)``);
- summaries are transitive (fixed point, so ``def a(): return b()``
  chains resolve), and consumption follows JLT002's conservative rule:
  any non-deriver call a tracked key is passed to consumes it.

Names already tracked by JLT002 (key-named parameters, direct deriver
assignments) are deliberately NOT re-tracked here — a reuse either rule
can see reports exactly once, under the rule that saw it first.

Known limits (documented in docs/STATIC_ANALYSIS.md): resolution is
name-based (no inheritance, no instance-attribute indirection), tuple
passthrough positions must be literal, and loop bodies are walked
twice like JLT002's.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..engine import FileContext, Finding
from . import Rule, iter_statements_ordered, shallow_walk
from .jlt002_key_reuse import _DERIVERS, _KEY_PARAM, _State, \
    _key_expr_name


def _is_deriver(ctx, call: ast.Call) -> bool:
    canon = ctx.canonical(call.func) or ""
    return (canon.startswith("jax.random.")
            and canon.rsplit(".", 1)[-1] in _DERIVERS)


def _is_jax_random(ctx, call: ast.Call) -> bool:
    canon = ctx.canonical(call.func) or ""
    return canon.startswith("jax.random.")


class _Summary:
    """What one function does with keys, from its caller's view."""

    __slots__ = ("returns_fresh", "passthrough", "consumes")

    def __init__(self) -> None:
        #: return positions yielding a fresh key (-1 = the whole
        #: return value; 0.. = literal tuple elements)
        self.returns_fresh: Set[int] = set()
        #: return position -> parameter index it passes through
        self.passthrough: Dict[int, int] = {}
        #: parameter indexes the body consumes (draws from)
        self.consumes: Set[int] = set()


def _summaries(project) -> Dict[str, _Summary]:
    """Fixed point of per-function key summaries over the call graph."""
    cached = project.cache.get("jlt008")
    if cached is not None:
        return cached
    sums: Dict[str, _Summary] = {fi.key: _Summary()
                                 for fi in project.functions.values()}
    for _ in range(6):  # call chains deeper than this do not resolve
        changed = False
        for fi in project.functions.values():
            if _summarize(project, fi, sums):
                changed = True
        if not changed:
            break
    project.cache["jlt008"] = sums
    return sums


def _summarize(project, fi, sums: Dict[str, _Summary]) -> bool:
    ctx = fi.ctx
    s = sums[fi.key]
    before = (frozenset(s.returns_fresh), tuple(sorted(s.passthrough.items())),
              frozenset(s.consumes))
    params = {p: i for i, p in enumerate(fi.params)}
    key_params = {p for p in fi.params if _KEY_PARAM.search(p)}
    # local names known to hold a key (derivers + fresh-returning calls)
    fresh_locals: Set[str] = set()
    for stmt in iter_statements_ordered(fi.node.body):
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value,
                                                       ast.Call):
            call = stmt.value
            fresh = _is_deriver(ctx, call)
            if not fresh:
                callee = project.resolve_call(ctx, call, cls=fi.cls)
                fresh = callee is not None \
                    and bool(sums[callee.key].returns_fresh)
            if fresh:
                for tgt in stmt.targets:
                    elts = tgt.elts if isinstance(
                        tgt, (ast.Tuple, ast.List)) else [tgt]
                    for el in elts:
                        if isinstance(el, ast.Name):
                            fresh_locals.add(el.id)
        for node in shallow_walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            if _is_jax_random(ctx, node) and not _is_deriver(ctx, node):
                for arg in list(node.args) + [k.value
                                              for k in node.keywords]:
                    if isinstance(arg, ast.Name) and arg.id in params:
                        s.consumes.add(params[arg.id])
                continue
            callee = project.resolve_call(ctx, node, cls=fi.cls)
            if callee is None:
                continue
            csum = sums[callee.key]
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(arg, ast.Name) and arg.id in params:
                    idx = callee.param_index(node, arg)
                    if idx is not None and idx in csum.consumes:
                        s.consumes.add(params[arg.id])
        if isinstance(stmt, ast.Return) and stmt.value is not None:
            _summarize_return(ctx, project, fi, stmt.value, params,
                              key_params, fresh_locals, sums, s)
    after = (frozenset(s.returns_fresh), tuple(sorted(s.passthrough.items())),
             frozenset(s.consumes))
    return after != before


def _summarize_return(ctx, project, fi, value, params, key_params,
                      fresh_locals, sums, s: _Summary) -> None:
    if isinstance(value, ast.Tuple):
        items: List[Tuple[int, ast.AST]] = list(enumerate(value.elts))
    else:
        items = [(-1, value)]
    for pos, el in items:
        if isinstance(el, ast.Call):
            if _is_deriver(ctx, el):
                s.returns_fresh.add(pos)
            else:
                callee = project.resolve_call(ctx, el, cls=fi.cls)
                if callee is not None \
                        and sums[callee.key].returns_fresh:
                    s.returns_fresh.add(pos)
        elif isinstance(el, ast.Name):
            if el.id in key_params:
                s.passthrough[pos] = params[el.id]
            elif el.id in fresh_locals:
                s.returns_fresh.add(pos)


class KeyFlowRule(Rule):
    id = "JLT008"
    name = "key-flow"
    summary = ("PRNG key crossing a function boundary (returned or "
               "passed through) consumed twice")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        project = ctx.project
        if project is None:
            return iter(())
        sums = _summaries(project)
        out: List[Finding] = []
        for fi in project.functions_in(ctx):
            state = _State()
            origin: Dict[str, str] = {}  # tracked name -> provenance
            self._walk_block(ctx, project, fi, sums, fi.node.body,
                             state, origin, out)
        return iter(out)

    # -- statement walking (JLT002's shape: branch merge, loops x2) ----
    def _walk_block(self, ctx, project, fi, sums, stmts, state, origin,
                    out) -> None:
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            if isinstance(s, ast.If):
                a, b = state.clone(), state.clone()
                self._walk_block(ctx, project, fi, sums, s.body, a,
                                 origin, out)
                self._walk_block(ctx, project, fi, sums, s.orelse, b,
                                 origin, out)
                state.merge(a, b)
            elif isinstance(s, (ast.For, ast.AsyncFor, ast.While)):
                self._walk_block(ctx, project, fi, sums, s.body, state,
                                 origin, out)
                self._walk_block(ctx, project, fi, sums, s.body, state,
                                 origin, out)
                self._walk_block(ctx, project, fi, sums, s.orelse,
                                 state, origin, out)
            elif isinstance(s, ast.With):
                self._walk_block(ctx, project, fi, sums, s.body, state,
                                 origin, out)
            elif isinstance(s, ast.Try):
                self._walk_block(ctx, project, fi, sums, s.body, state,
                                 origin, out)
                for h in s.handlers:
                    self._walk_block(ctx, project, fi, sums, h.body,
                                     state.clone(), origin, out)
                self._walk_block(ctx, project, fi, sums, s.finalbody,
                                 state, origin, out)
            else:
                for node in ast.walk(s):
                    if isinstance(node, ast.Call):
                        self._consume(ctx, node, state, origin, out)
                if isinstance(s, ast.Assign):
                    self._assign(ctx, project, fi, sums, s, state,
                                 origin)

    # -- consumption ---------------------------------------------------
    def _consume(self, ctx, call, state: _State, origin, out) -> None:
        if _is_deriver(ctx, call):
            return
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            name = _key_expr_name(arg)
            if name is None or name not in state.gen:
                continue
            gen = state.gen[name]
            prev = state.used.get(name)
            if prev is not None and prev[0] == gen:
                out.append(self.finding(
                    ctx, call,
                    "key %r (%s) already consumed at line %d — a key "
                    "that crossed a function boundary is still ONE "
                    "stream; split/fold_in before drawing again"
                    % (name, origin.get(name, "cross-function key"),
                       prev[1])))
            else:
                state.used[name] = (gen, call.lineno)

    # -- binding -------------------------------------------------------
    def _assign(self, ctx, project, fi, sums, stmt, state: _State,
                origin) -> None:
        value = stmt.value
        if not isinstance(value, ast.Call):
            # overwriting a tracked name with a non-call drops tracking
            for tgt in stmt.targets:
                name = _key_expr_name(tgt)
                if name in state.gen:
                    del state.gen[name]
                    state.used.pop(name, None)
            return
        if _is_deriver(ctx, value):
            return  # JLT002's territory: direct deriver assignment
        callee = project.resolve_call(ctx, value, cls=fi.cls)
        if callee is None:
            return
        csum = sums[callee.key]
        if not csum.returns_fresh and not csum.passthrough:
            return
        for tgt in stmt.targets:
            if isinstance(tgt, (ast.Tuple, ast.List)):
                positions = list(enumerate(tgt.elts))
            else:
                positions = [(-1, tgt)]
            for pos, el in positions:
                name = _key_expr_name(el)
                if name is None:
                    continue
                if _KEY_PARAM.search(name.rsplit(".", 1)[-1]):
                    continue  # JLT002 already tracks key-named targets
                if pos in csum.returns_fresh:
                    state.gen[name] = state.gen.get(name, -1) + 1
                    state.used.pop(name, None)
                    origin[name] = ("fresh key returned by %s()"
                                    % callee.qualname)
                elif pos in csum.passthrough:
                    pidx = csum.passthrough[pos]
                    state.gen[name] = state.gen.get(name, -1) + 1
                    state.used.pop(name, None)
                    origin[name] = ("key passed through %s()"
                                    % callee.qualname)
                    if pidx in csum.consumes:
                        # the callee already drew from it: the target
                        # is born consumed
                        state.used[name] = (state.gen[name],
                                            value.lineno)
