"""JLT006 — dtype-widening hazards in the quantized histogram modules.

PR 4 made integer histogram dtypes load-bearing: int8/int16 gh rows
accumulate into int32/int64 histograms whose sums are EXACT (bit-exact
sibling subtraction, exact zero-bin residuals). A stray Python float
literal in that data path silently promotes everything back to f32 —
correctness quietly degrades to the old accumulation-order drift and
the bandwidth win evaporates (4x the bytes). The fix idiom is a
dtype-preserving neutral element: ``zero = jnp.zeros((), dtype=g.dtype)``
then ``jnp.where(mask, x, zero)``.

Two checks, scoped to the quantized modules (engine.QUANT_MODULES):

- a ``jnp.where`` whose arms mix a float literal with a non-float
  value (the literal promotes the other arm);
- arithmetic between a float literal and a name that locally carries
  an integer dtype (assigned via ``.astype(jnp.int8/16/32/64)``, a
  ``dtype=jnp.intNN`` keyword, or ``sum_gh``).
"""
from __future__ import annotations

import ast
from typing import Iterator, Set

from ..engine import FileContext, Finding
from . import Rule, const_float, iter_statements_ordered, \
    shallow_walk, walk_scopes

_INT_DTYPES = {"int8", "int16", "int32", "int64", "uint8", "uint16",
               "uint32", "uint64"}


def _int_dtype_expr(ctx, node: ast.AST) -> bool:
    canon = ctx.canonical(node) or ""
    return canon.rsplit(".", 1)[-1] in _INT_DTYPES or (
        isinstance(node, ast.Constant) and node.value in _INT_DTYPES)


def _int_producer(ctx, value: ast.AST) -> bool:
    """Does this expression locally announce an integer dtype?"""
    if not isinstance(value, ast.Call):
        return False
    func = value.func
    if isinstance(func, ast.Attribute) and func.attr == "astype" \
            and value.args and _int_dtype_expr(ctx, value.args[0]):
        return True
    canon = ctx.canonical(func) or ""
    if canon.rsplit(".", 1)[-1] == "sum_gh":
        return True
    for kw in value.keywords:
        if kw.arg == "dtype" and _int_dtype_expr(ctx, kw.value):
            return True
    return False


class DtypeWideningRule(Rule):
    id = "JLT006"
    name = "dtype-widening"
    summary = ("float literal promoting the integer histogram dtype "
               "in a quantized module")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.is_quant_module:
            return
        for scope in walk_scopes(ctx.tree):
            yield from self._check_scope(ctx, scope)

    def _check_scope(self, ctx, scope) -> Iterator[Finding]:
        int_names: Set[str] = set()
        # statement-granular ordering (see jlt001): int-dtype bindings
        # inside a with/loop/if body must be visible to later
        # statements of the same block
        for stmt in iter_statements_ordered(scope.body):
            nodes = sorted(shallow_walk(stmt),
                           key=lambda n: (getattr(n, "lineno", 0),
                                          getattr(n, "col_offset", 0)))
            for node in nodes:
                yield from self._check_node(ctx, node, int_names)
            for node in nodes:
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    tgt = node.targets[0].id
                    if _int_producer(ctx, node.value):
                        int_names.add(tgt)
                    else:
                        int_names.discard(tgt)

    def _check_node(self, ctx, node, int_names) -> Iterator[Finding]:
        if isinstance(node, ast.Call):
            canon = ctx.canonical(node.func) or ""
            if canon.rsplit(".", 1)[-1] == "where" \
                    and canon.startswith(("jax.numpy", "jnp")) \
                    and len(node.args) == 3:
                a, b = node.args[1], node.args[2]
                if const_float(a) != const_float(b):
                    yield self.finding(
                        ctx, node,
                        "jnp.where arm is a float literal: it promotes "
                        "the integer histogram dtype to f32 — use a "
                        "dtype-preserving neutral element "
                        "(jnp.zeros((), dtype=x.dtype)) or an int "
                        "literal")
        elif isinstance(node, ast.BinOp):
            l, r = node.left, node.right
            for lit, other in ((l, r), (r, l)):
                if const_float(lit) and isinstance(other, ast.Name) \
                        and other.id in int_names:
                    yield self.finding(
                        ctx, node,
                        "float literal in arithmetic with %r (integer "
                        "histogram data): the result silently promotes "
                        "to f32 — dequantize once via "
                        "ops/quantize.dequantize_hist instead"
                        % other.id)
                    break
