"""JLT005 — collectives must be named and attributable.

Two invariants from the mesh learners:

1. every collective (``psum``/``all_gather``/``ppermute``/...) names
   its mesh axis — an axis-less collective either fails late inside
   ``shard_map``/``pmap`` or silently reduces over the wrong axis when
   meshes gain a second dimension;
2. every collective sits inside a ``jax.named_scope("obs_psum_*")``
   block, so the XLA-inserted cross-device reduce is attributable in
   device traces (PR 1's convention; tools/trace_report.py groups
   device time by these names). A bare psum is untraceable bytes on
   the interconnect.
"""
from __future__ import annotations

import ast
from typing import Iterator

from ..engine import FileContext, Finding
from . import Rule

_COLLECTIVES = {"psum", "pmean", "pmax", "pmin", "all_gather",
                "ppermute", "all_to_all", "psum_scatter", "pshuffle"}
_SCOPE_PREFIX = "obs_psum_"


def _scope_name(with_node: ast.With):
    for item in with_node.items:
        call = item.context_expr
        if isinstance(call, ast.Call) and call.args:
            arg = call.args[0]
            func = call.func
            is_named_scope = (isinstance(func, ast.Attribute)
                              and func.attr == "named_scope") or \
                             (isinstance(func, ast.Name)
                              and func.id == "named_scope")
            if is_named_scope and isinstance(arg, ast.Constant) \
                    and isinstance(arg.value, str):
                return arg.value
            if is_named_scope:
                return ""  # dynamic name: treat as unknown-but-named
    return None


class CollectivesRule(Rule):
    id = "JLT005"
    name = "collectives"
    summary = ("collective without axis_name or outside an obs_psum_* "
               "named scope")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        yield from self._visit(ctx, ctx.tree, in_scope=False)

    def _visit(self, ctx, node, in_scope: bool) -> Iterator[Finding]:
        for child in ast.iter_child_nodes(node):
            child_in_scope = in_scope
            if isinstance(child, ast.With):
                name = _scope_name(child)
                if name is not None:
                    # a dynamic (non-literal) named_scope counts as
                    # named: the data-parallel learner picks between
                    # obs_psum_* strings at trace time
                    child_in_scope = in_scope or name == "" \
                        or name.startswith(_SCOPE_PREFIX)
            if isinstance(child, ast.Call):
                yield from self._check_call(ctx, child, child_in_scope)
            yield from self._visit(ctx, child, child_in_scope)

    def _check_call(self, ctx, call: ast.Call,
                    in_scope: bool) -> Iterator[Finding]:
        canon = ctx.canonical(call.func) or ""
        tail = canon.rsplit(".", 1)[-1]
        if tail not in _COLLECTIVES:
            return
        if not (canon.startswith("jax.lax.") or canon.startswith("lax.")
                or canon.startswith("jax.")):
            return
        has_axis = len(call.args) >= 2 or any(
            kw.arg == "axis_name" for kw in call.keywords)
        if not has_axis:
            yield self.finding(
                ctx, call,
                "%s without an axis_name: name the mesh axis "
                "explicitly — axis-less collectives break (or reduce "
                "over the wrong axis) the moment the mesh gains a "
                "second dimension" % tail)
        if not in_scope:
            yield self.finding(
                ctx, call,
                "%s outside a jax.named_scope(\"obs_psum_*\") block: "
                "wrap it so the cross-device reduce is attributable "
                "in device traces (tools/trace_report.py groups on "
                "these names)" % tail)
