"""Rule registry. Each rule module exposes one ``Rule`` subclass;
register it here and it participates in every run, ``--select``, and
``--list-rules``."""
from __future__ import annotations

import ast
from typing import Iterator, List, Optional

from ..engine import FileContext, Finding


class Rule:
    """Base: subclasses set ``id``/``name``/``summary`` and implement
    :meth:`check` yielding findings for one parsed file."""

    id: str = ""
    name: str = ""
    summary: str = ""

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, ctx: FileContext, node: ast.AST,
                message: str) -> Finding:
        return Finding(self.id, ctx.path, getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0), message)


def scope_statements(scope: ast.AST) -> List[ast.stmt]:
    """Direct statements of a scope (module or function body), for
    rules that need statement ORDER. Nested function/class bodies are
    their own scopes and are excluded."""
    body = getattr(scope, "body", [])
    return list(body)


def walk_scopes(tree: ast.Module):
    """Yield every scope node: the module, each class body (for
    class-level assignments) and each (async) function — lambdas ride
    along in their enclosing scope."""
    yield tree
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            yield node


def _stmt_blocks(stmt: ast.stmt):
    """Nested statement blocks of a compound statement (with/for/if/
    try bodies), in source order."""
    for field in ("body", "orelse", "finalbody"):
        blk = getattr(stmt, field, None)
        if isinstance(blk, list) and blk \
                and isinstance(blk[0], ast.stmt):
            yield blk
    for handler in getattr(stmt, "handlers", []) or []:
        yield handler.body


def iter_statements_ordered(body):
    """Every statement of a scope in source order, RECURSING into
    compound-statement bodies (with/for/if/try) but not into nested
    function/class definitions. Pair each yielded statement with
    :func:`shallow_walk` to visit its own expressions exactly once —
    taint-tracking rules need assignments inside a ``with`` or loop
    body to take effect before later statements of the same block."""
    for s in body:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            continue
        yield s
        for blk in _stmt_blocks(s):
            yield from iter_statements_ordered(blk)


def shallow_walk(stmt: ast.stmt):
    """Walk one statement's own expressions: nested statements (a
    compound statement's body) and nested defs are NOT descended into —
    they are yielded separately by :func:`iter_statements_ordered`."""
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return
    stack = [stmt]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt) \
                    or isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.ClassDef)):
                continue
            stack.append(child)


def walk_in_scope(stmt: ast.stmt):
    """ast.walk over one statement, NOT descending into nested
    function/class definitions (those are separate scopes, visited via
    :func:`walk_scopes`). A def/class at the root yields nothing for
    the same reason."""
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return
    stack = [stmt]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            stack.append(child)


def const_float(node: ast.AST) -> bool:
    """A float literal, including a negated one (``-1.0``)."""
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        node = node.operand
    return isinstance(node, ast.Constant) and isinstance(node.value, float)


from .jlt001_host_sync import HostSyncRule          # noqa: E402
from .jlt002_key_reuse import KeyReuseRule          # noqa: E402
from .jlt003_raw_jit import RawJitRule              # noqa: E402
from .jlt004_static_args import StaticArgsRule      # noqa: E402
from .jlt005_collectives import CollectivesRule     # noqa: E402
from .jlt006_dtype_widening import DtypeWideningRule  # noqa: E402
from .jlt008_key_flow import KeyFlowRule            # noqa: E402
from .jlt009_static_callsites import StaticCallSiteRule  # noqa: E402
from .jlt010_pallas import PallasInvariantsRule     # noqa: E402
from .jlt1xx_concurrency import (                   # noqa: E402
    BlockingUnderLockRule, LockOrderRule, UnlockedSharedMutationRule)

RULES = {r.id: r for r in (
    HostSyncRule(), KeyReuseRule(), RawJitRule(), StaticArgsRule(),
    CollectivesRule(), DtypeWideningRule(), KeyFlowRule(),
    StaticCallSiteRule(), PallasInvariantsRule(),
    UnlockedSharedMutationRule(), BlockingUnderLockRule(),
    LockOrderRule())}
