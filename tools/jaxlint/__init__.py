"""jaxlint — TPU hot-path static analysis for lightgbm_tpu.

A stdlib-only (ast + tokenize) analyzer whose rules encode this repo's
hard-won jax invariants — each one a bug class that was originally
found by hand in review and is now machine-checked:

========  ==============================================================
JLT001    host-device sync in hot-path modules (``.item()``,
          ``float()/int()/bool()`` on jax values, ``np.asarray`` of jax
          values, ``jax.device_get``, ``block_until_ready``)
JLT002    PRNG key reuse (one key consumed by two ``jax.random`` draws
          with no interleaving ``split``/``fold_in``)
JLT003    raw ``jax.jit`` call sites that bypass
          ``obs/compile.instrument_jit`` (untracked compiles)
JLT004    unhashable / churn-prone static args (list/dict literals
          reaching ``static_argnums``/``static_argnames`` positions)
JLT005    collectives without an ``axis_name`` or outside an
          ``obs_psum_*`` named scope
JLT006    dtype-widening hazards in the quantized histogram modules
          (float literals silently promoting int8/int16 data)
JLT000    a ``# jaxlint: disable=...`` suppression with no rationale
==========================================================================

Suppress a finding with a trailing (or immediately preceding) comment
naming the rule AND the reason::

    x = jax.device_get(rec)  # jaxlint: disable=JLT001 -- per-tree sync

Run: ``python -m tools.jaxlint lightgbm_tpu`` (non-zero exit on
findings; ``--format json`` for machine consumption). See
docs/STATIC_ANALYSIS.md for the rule catalog and how to add a rule.
"""
from .engine import Finding, check_file, check_source, run  # noqa: F401

__version__ = "1.0"
