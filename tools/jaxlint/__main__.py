"""CLI: ``python -m tools.jaxlint [paths] [--format text|json] ...``

Exit status: 0 when clean, 1 on findings (use ``--exit-zero`` to
report without gating), 2 on usage errors — so the tier-1 test suite
and any CI job can run the analyzer as a standalone gate.
"""
from __future__ import annotations

import argparse
import json
import sys

from . import __version__
from .engine import run
from .rules import RULES


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.jaxlint",
        description="TPU hot-path static analysis for lightgbm_tpu")
    ap.add_argument("paths", nargs="*", default=["lightgbm_tpu"],
                    help="files or package directories "
                         "(default: lightgbm_tpu)")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text")
    ap.add_argument("--select", metavar="RULES",
                    help="comma-separated rule ids to run "
                         "(default: all)")
    ap.add_argument("--exit-zero", action="store_true",
                    help="always exit 0 (report-only mode)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--version", action="version",
                    version="jaxlint " + __version__)
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, rule in sorted(RULES.items()):
            print("%s  %-14s %s" % (rid, rule.name, rule.summary))
        print("JLT000  %-14s %s" % ("bare-disable",
                                    "suppression without a rationale"))
        print("JLT007  %-14s %s" % ("unused-disable",
                                    "suppression that suppresses "
                                    "nothing"))
        return 0

    select = args.select.split(",") if args.select else None
    report = run(args.paths or ["lightgbm_tpu"], select=select)
    findings = report.pop("_findings")

    if args.format == "json":
        json.dump(report, sys.stdout, indent=2)
        print()
    else:
        for f in findings:
            print(f.text())
        print("jaxlint: %d finding%s (%d suppressed) in %d file%s"
              % (len(findings), "s" * (len(findings) != 1),
                 report["suppressed"], report["files_scanned"],
                 "s" * (report["files_scanned"] != 1)))
    if findings and not args.exit_zero:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
