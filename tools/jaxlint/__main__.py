"""CLI: ``python -m tools.jaxlint [paths] [--format text|json] ...``

Exit status: 0 when clean, 1 on findings (use ``--exit-zero`` to
report without gating), 2 on usage errors — so the tier-1 test suite
and any CI job can run the analyzer as a standalone gate.

Baseline mode (``--baseline FILE``) supports landing a new rule
against a codebase with pre-existing findings: ``--write-baseline``
snapshots today's findings; later runs against the same file report
and gate ONLY on findings not in the snapshot, so new violations
fail while the known backlog burns down independently.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List

from . import __version__
from .engine import Finding, run
from .rules import RULES

_BASELINE_VERSION = 1


def _fingerprint(f: Finding) -> str:
    """Line-number-free identity: findings keep matching their
    baseline entry while unrelated edits shift the file."""
    return "%s|%s|%s" % (f.rule, f.path, f.message)


def _baseline_counts(findings: List[Finding]) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for f in findings:
        fp = _fingerprint(f)
        out[fp] = out.get(fp, 0) + 1
    return out


def _write_baseline(path: str, findings: List[Finding]) -> None:
    doc = {"jaxlint_baseline": _BASELINE_VERSION,
           "entries": _baseline_counts(findings)}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")


def _load_baseline(path: str) -> Dict[str, int]:
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict) \
            or doc.get("jaxlint_baseline") != _BASELINE_VERSION:
        raise ValueError("not a jaxlint baseline file: %s" % path)
    entries = doc.get("entries", {})
    return {str(k): int(v) for k, v in entries.items()}


def _new_findings(findings: List[Finding],
                  baseline: Dict[str, int]) -> List[Finding]:
    """Findings beyond the baselined count per fingerprint — a second
    occurrence of a known finding is still NEW."""
    budget = dict(baseline)
    out = []
    for f in findings:
        fp = _fingerprint(f)
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
        else:
            out.append(f)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.jaxlint",
        description="TPU hot-path static analysis for lightgbm_tpu")
    ap.add_argument("paths", nargs="*", default=["lightgbm_tpu"],
                    help="files or package directories "
                         "(default: lightgbm_tpu)")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text")
    ap.add_argument("--select", metavar="RULES",
                    help="comma-separated rule ids to run "
                         "(default: all)")
    ap.add_argument("--exit-zero", action="store_true",
                    help="always exit 0 (report-only mode)")
    ap.add_argument("--baseline", metavar="FILE",
                    help="findings snapshot: gate only on findings "
                         "NOT in FILE (see --write-baseline)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the current findings to --baseline "
                         "FILE and exit 0")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--version", action="version",
                    version="jaxlint " + __version__)
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, rule in sorted(RULES.items()):
            print("%s  %-14s %s" % (rid, rule.name, rule.summary))
        print("JLT000  %-14s %s" % ("bare-disable",
                                    "suppression without a rationale"))
        print("JLT007  %-14s %s" % ("unused-disable",
                                    "suppression that suppresses "
                                    "nothing"))
        return 0

    if args.write_baseline and not args.baseline:
        ap.error("--write-baseline requires --baseline FILE")

    select = args.select.split(",") if args.select else None
    report = run(args.paths or ["lightgbm_tpu"], select=select)
    findings = report.pop("_findings")

    if args.baseline and args.write_baseline:
        _write_baseline(args.baseline, findings)
        print("jaxlint: wrote baseline of %d finding%s to %s"
              % (len(findings), "s" * (len(findings) != 1),
                 args.baseline))
        return 0
    if args.baseline:
        try:
            baseline = _load_baseline(args.baseline)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print("jaxlint: cannot read baseline: %s" % e,
                  file=sys.stderr)
            return 2
        known = len(findings)
        findings = _new_findings(findings, baseline)
        report["findings"] = [f.as_dict() for f in findings]
        report["baseline"] = {"known": known - len(findings),
                              "new": len(findings)}

    if args.format == "json":
        json.dump(report, sys.stdout, indent=2)
        print()
    else:
        for f in findings:
            print(f.text())
        tail = ""
        if "baseline" in report:
            tail = ", %d known baselined" % report["baseline"]["known"]
        print("jaxlint: %d finding%s (%d suppressed%s) in %d file%s"
              % (len(findings), "s" * (len(findings) != 1),
                 report["suppressed"], tail, report["files_scanned"],
                 "s" * (report["files_scanned"] != 1)))
    if findings and not args.exit_zero:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
