"""jaxlint engine: file discovery, import-alias resolution, suppression
parsing, and rule driving.

The engine owns everything rule-independent: it parses each file once,
builds a :class:`FileContext` (AST + canonical-dotted-name resolver +
module classification), asks every registered rule for findings, and
filters them through ``# jaxlint: disable=RULE -- reason`` comments.
Rules live in :mod:`tools.jaxlint.rules` and never read files
themselves, so adding a rule is one visitor module + one registry line.
"""
from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: package-relative path prefixes/files where host syncs are the job
#: (event sinks, the serving front-end) — JLT001 does not apply there.
HOST_SYNC_EXEMPT = ("obs/", "serve/server.py")

#: modules whose arrays carry the int8/int16 quantized histogram dtype
#: (JLT006's scope): a stray float literal silently promotes them.
QUANT_MODULES = ("ops/histogram.py", "ops/quantize.py")

#: the one module allowed to spell ``jax.jit`` (JLT003): every other
#: site must go through its ``instrument_jit`` so compiles are counted.
JIT_OWNER = ("obs/compile.py",)

#: modules whose classes run worker threads against shared state — the
#: JLT10x concurrency-discipline family applies here (and only here:
#: single-threaded modules get no value from lock-discipline findings).
THREADED_MODULES = ("serve/", "loop/", "obs/gateway.py",
                    "obs/export.py", "io/shards.py")

_SUPPRESS_RE = re.compile(
    r"#\s*jaxlint:\s*disable=([A-Z]{3}\d{3}(?:\s*,\s*[A-Z]{3}\d{3})*)"
    r"(?:\s*--\s*(\S.*?))?\s*$")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str

    def text(self) -> str:
        return "%s:%d:%d: %s %s" % (self.path, self.line, self.col + 1,
                                    self.rule, self.message)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class FileContext:
    """Everything a rule needs about one file: the AST, source lines,
    the scan-root-relative posix path, and import-alias resolution."""

    def __init__(self, source: str, path: str, relpath: str):
        self.source = source
        self.path = path
        self.relpath = relpath.replace("\\", "/")
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self._aliases = _import_aliases(self.tree)
        #: set by ProjectIndex — every rule run sees a project (a
        #: single-file run gets a one-file index)
        self.project = None
        self.module = ""

    # -- module classification -----------------------------------------
    @property
    def is_test(self) -> bool:
        name = self.relpath.rsplit("/", 1)[-1]
        return (name.startswith("test_") or "/tests/" in "/" + self.relpath)

    @property
    def host_sync_exempt(self) -> bool:
        return self.is_test or _matches(self.relpath, HOST_SYNC_EXEMPT)

    @property
    def is_quant_module(self) -> bool:
        return _matches(self.relpath, QUANT_MODULES)

    @property
    def owns_jit(self) -> bool:
        return _matches(self.relpath, JIT_OWNER)

    @property
    def is_threaded_module(self) -> bool:
        return (not self.is_test
                and _matches(self.relpath, THREADED_MODULES))

    # -- name resolution -----------------------------------------------
    def canonical(self, node: ast.AST) -> Optional[str]:
        """Fully-resolved dotted name of a Name/Attribute chain, with
        import aliases expanded (``jnp.where`` → ``jax.numpy.where``,
        relative imports keep their module tail: ``obs_compile.x`` from
        ``from ..obs import compile as obs_compile`` → ``obs.compile.x``).
        None for anything that is not a plain dotted chain."""
        parts = _dotted(node)
        if not parts:
            return None
        head = self._aliases.get(parts[0], parts[0])
        return ".".join([head] + parts[1:]) if len(parts) > 1 else head


def _matches(relpath: str, patterns: Sequence[str]) -> bool:
    rp = relpath
    for pat in patterns:
        if pat.endswith("/"):
            if rp.startswith(pat) or ("/" + pat) in ("/" + rp):
                return True
        elif rp == pat or rp.endswith("/" + pat):
            return True
    return False


def _dotted(node: ast.AST) -> Optional[List[str]]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


def _import_aliases(tree: ast.Module) -> Dict[str, str]:
    """local name → canonical dotted module. Relative imports resolve
    to their module tail (``from ..obs import compile as obs_compile``
    → ``obs.compile``): rules match on suffixes like ``instrument_jit``
    or roots like ``jax``, so the exact package prefix is irrelevant."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            for a in node.names:
                if a.name == "*":
                    continue
                full = (mod + "." + a.name).lstrip(".") if mod else a.name
                out[a.asname or a.name] = full
    return out


# ----------------------------------------------------------------------
# suppressions
# ----------------------------------------------------------------------

class Suppressions:
    """Per-line ``# jaxlint: disable=RULE[,RULE] [-- reason]`` map.

    A trailing comment suppresses its own line. A standalone comment
    line suppresses the first following line of code (consecutive
    comment lines chain, so a two-line rationale above a statement
    works). Suppressions WITHOUT a rationale still suppress — but the
    engine reports each one as a JLT000 finding, so an unjustified
    suppression cannot pass the gate silently.

    Directives are read from real COMMENT tokens (``tokenize``), never
    from raw line text — suppression syntax quoted inside a docstring
    (as documentation tends to do) neither suppresses anything nor
    produces a phantom JLT000.
    """

    def __init__(self, source):
        if not isinstance(source, str):
            source = "\n".join(source) + "\n"
        comments: Dict[int, Tuple[set, bool, bool]] = {}
        code_lines: set = set()
        skip_types = {tokenize.COMMENT, tokenize.NL, tokenize.NEWLINE,
                      tokenize.INDENT, tokenize.DEDENT,
                      tokenize.ENCODING, tokenize.ENDMARKER}
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(source).readline))
        except (tokenize.TokenError, IndentationError):
            tokens = []
        for tok in tokens:
            if tok.type == tokenize.COMMENT:
                m = _SUPPRESS_RE.search(tok.string)
                if m:
                    rules = {r.strip() for r in m.group(1).split(",")}
                    standalone = not tok.line[:tok.start[1]].strip()
                    comments[tok.start[0]] = (rules, bool(m.group(2)),
                                              standalone)
            elif tok.type not in skip_types:
                for ln in range(tok.start[0], tok.end[0] + 1):
                    code_lines.add(ln)
        self.by_line: Dict[int, set] = {}
        self.bare: List[Tuple[int, str]] = []
        #: every directive as (directive_line, rules, covered_code_line)
        #: — what the unused-suppression detector (JLT007) audits; a
        #: standalone directive with no following code covers None.
        self.directives: List[Tuple[int, frozenset, Optional[int]]] = []
        n_lines = source.count("\n") + 1
        pending: List[Tuple[int, set]] = []
        for i in range(1, n_lines + 1):
            entry = comments.get(i)
            if entry is not None:
                rules, has_reason, standalone = entry
                if not has_reason:
                    self.bare.append((i, ",".join(sorted(rules))))
                if standalone:
                    pending.append((i, rules))
                    continue
                self.by_line.setdefault(i, set()).update(rules)
                self.directives.append((i, frozenset(rules), i))
            if i in code_lines:
                for dline, rules in pending:
                    self.by_line.setdefault(i, set()).update(rules)
                    self.directives.append((dline, frozenset(rules), i))
                pending = []
            # blank and plain-comment lines keep pending alive
        for dline, rules in pending:  # directive with no code after it
            self.directives.append((dline, frozenset(rules), None))

    def active(self, rule: str, line: int) -> bool:
        return rule in self.by_line.get(line, ())


# ----------------------------------------------------------------------
# driving
# ----------------------------------------------------------------------

def expand_select(select: Iterable[str]) -> set:
    """Normalize a ``--select`` list: uppercase, and expand a trailing
    ``x``/``X`` as a family wildcard (``JLT10x`` → every registered
    rule whose id starts with ``JLT10``)."""
    from .rules import RULES
    wanted = set()
    for s in select:
        tok = s.strip().upper()
        if tok.endswith("X") and len(tok) > 4:
            family = {rid for rid in RULES if rid.startswith(tok[:-1])}
            if not family:
                raise SystemExit("rule family %r matches nothing "
                                 "(known: %s)"
                                 % (s.strip(), ", ".join(sorted(RULES))))
            wanted |= family
        else:
            wanted.add(tok)
    return wanted


def _rules(select: Optional[Iterable[str]] = None):
    from .rules import RULES
    if select is None:
        return list(RULES.values())
    wanted = expand_select(select)
    wanted.discard("JLT000")  # engine-level rules, always available
    wanted.discard("JLT007")
    unknown = wanted - set(RULES)
    if unknown:
        raise SystemExit("unknown rule id(s): %s (known: %s)"
                         % (", ".join(sorted(unknown)),
                            ", ".join(sorted(RULES))))
    return [r for rid, r in RULES.items() if rid in wanted]


def check_source(source: str, relpath: str = "<string>",
                 select: Optional[Iterable[str]] = None,
                 path: Optional[str] = None
                 ) -> Tuple[List[Finding], int]:
    """Lint one source string; returns (findings, n_suppressed).
    ``relpath`` drives module classification (pass e.g.
    ``"treelearner/serial.py"`` to simulate a package location). The
    project index covers just this file, so cross-function rules see
    intra-file flow only."""
    from .project import ProjectIndex
    ctx = FileContext(source, path or relpath, relpath)
    ProjectIndex([ctx])
    return _check_ctx(ctx, select)


def _check_ctx(ctx: FileContext,
               select: Optional[Iterable[str]] = None
               ) -> Tuple[List[Finding], int]:
    """Run every selected rule over one already-indexed FileContext."""
    sup = Suppressions(ctx.source)
    rules_run = _rules(select)
    raw: List[Finding] = []
    for rule in rules_run:
        raw.extend(rule.check(ctx))
    # identical findings dedupe (e.g. JLT002 walks loop bodies twice —
    # a reuse in a loop must not be reported twice)
    raw = list(dict.fromkeys(raw))
    findings = [f for f in raw if not sup.active(f.rule, f.line)]
    suppressed = len(raw) - len(findings)
    sel = None if select is None else expand_select(select)
    if sel is None or "JLT000" in sel:
        for line, rules in sup.bare:
            findings.append(Finding(
                "JLT000", ctx.path, line, 0,
                "suppression of %s has no rationale — write "
                "'# jaxlint: disable=%s -- <why this is sound>'"
                % (rules, rules)))
    if sel is None or "JLT007" in sel:
        findings.extend(_unused_suppressions(ctx, sup, raw,
                                             {r.id for r in rules_run},
                                             full_run=sel is None))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, suppressed


def _unused_suppressions(ctx: FileContext, sup: Suppressions,
                         raw: List[Finding], ran_ids: set,
                         full_run: bool) -> List[Finding]:
    """JLT007 — a ``# jaxlint: disable=RULE`` that suppresses nothing.
    A directive is unused when the rule it names actually RAN and no
    raw finding of that rule landed on the line it covers (a rule
    excluded by ``--select`` is never judged — it might well fire on a
    full run). Also dead by construction: directives naming JLT000
    (bare-disable findings bypass suppression on purpose) and — on a
    full run — rule ids that do not exist. Stale disables are worse
    than noise: they grant a future regression at that line a free
    pass."""
    from .rules import RULES
    used = {(f.line, f.rule) for f in raw if sup.active(f.rule, f.line)}
    out: List[Finding] = []
    for dline, drules, covered in sup.directives:
        for rule in sorted(drules):
            if rule == "JLT000":
                why = ("JLT000 (bare disable) cannot be suppressed, "
                       "so this directive does nothing")
            elif rule in ran_ids:
                if covered is not None and (covered, rule) in used:
                    continue
                why = "it matches no %s finding" % rule
            elif full_run and rule not in RULES:
                why = "%s is not a known rule id" % rule
            else:
                continue  # rule excluded by --select: cannot judge
            out.append(Finding(
                "JLT007", ctx.path, dline, 0,
                "unused suppression of %s — %s; remove the stale "
                "directive (it would silently grant a future "
                "regression at this line a free pass)" % (rule, why)))
    return out


def check_file(path: str, root: Optional[str] = None,
               select: Optional[Iterable[str]] = None
               ) -> Tuple[List[Finding], int]:
    p = Path(path)
    rel = str(p.resolve().relative_to(Path(root).resolve())) if root \
        else p.name
    return check_source(p.read_text(encoding="utf-8"), rel,
                        select=select, path=str(p))


def _load_contexts(paths: Sequence[str]) -> List[FileContext]:
    out: List[FileContext] = []
    for f, root in iter_py_files(paths):
        p = Path(f)
        rel = str(p.resolve().relative_to(Path(root).resolve()))
        out.append(FileContext(p.read_text(encoding="utf-8"),
                               str(p), rel))
    return out


def _package_root(file_path: Path) -> Path:
    """Topmost ancestor directory that is itself a package (has an
    ``__init__.py``): linting ``lightgbm_tpu/obs/compile.py`` alone
    must classify it as ``obs/compile.py`` — the same relpath a
    package-directory scan produces — or per-file invocations would
    lose every path-scoped exemption."""
    root = file_path.parent
    while (root / "__init__.py").exists() and root.parent != root:
        root = root.parent
    if root == file_path.parent:
        return root
    # root is now one above the outermost package dir; anchor there so
    # relpaths start INSIDE the package ("obs/compile.py", not
    # "lightgbm_tpu/obs/compile.py" — patterns are package-relative)
    outer = file_path.parent
    while outer.parent != root:
        outer = outer.parent
    return outer


def iter_py_files(paths: Sequence[str]):
    """Yield (file, root) pairs; ``root`` anchors the relative path the
    module-classification patterns match against."""
    for raw in paths:
        p = Path(raw)
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if "__pycache__" in f.parts:
                    continue
                yield str(f), str(p)
        elif p.suffix == ".py":
            yield str(p), str(_package_root(p.resolve()))
        else:
            raise SystemExit("not a python file or directory: %s" % raw)


def run(paths: Sequence[str],
        select: Optional[Iterable[str]] = None) -> dict:
    """Lint ``paths`` (files or directory trees); returns the report
    dict the CLI renders (text or JSON). All files parse FIRST so the
    project index (symbol table + call graph) spans every scanned
    file; cross-function/cross-module rules then run per file against
    the shared index."""
    from .project import ProjectIndex
    contexts = _load_contexts(paths)
    ProjectIndex(contexts)
    findings: List[Finding] = []
    suppressed = 0
    n_files = len(contexts)
    for ctx in contexts:
        got, sup = _check_ctx(ctx, select)
        findings.extend(got)
        suppressed += sup
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return {
        "files_scanned": n_files,
        "findings": [f.as_dict() for f in findings],
        "counts": dict(sorted(counts.items())),
        "suppressed": suppressed,
        "_findings": findings,
    }
