"""Project-wide analysis: module symbol table + intra-package call
graph, built once per lint run from every parsed :class:`FileContext`.

The index is deliberately name-based and conservative (stdlib ``ast``
only, same engine architecture as the per-file pass):

- modules are keyed by their package-relative dotted name
  (``obs/compile.py`` → ``obs.compile``);
- a canonical dotted call name (from ``FileContext.canonical``, which
  resolves import aliases) is matched against module names by SUFFIX,
  because relative imports resolve to their module tail;
- ``self.method(...)`` resolves within the enclosing class only — no
  inheritance, no instance-attribute indirection
  (``self.registry.get(...)`` does not resolve);
- an ambiguous symbol (two modules ending in the same tail defining
  the same name) resolves to nothing rather than to a guess.

Rules reach the index through ``ctx.project`` and stash per-rule
computed summaries in ``project.cache`` so a full-package run computes
each fixed point exactly once.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional

__all__ = ["ProjectIndex", "FunctionInfo", "module_name"]


def module_name(relpath: str) -> str:
    """Dotted module name for a package-relative path
    (``serve/__init__.py`` → ``serve``)."""
    rp = relpath.replace("\\", "/")
    if rp.endswith(".py"):
        rp = rp[:-3]
    if rp.endswith("/__init__"):
        rp = rp[: -len("/__init__")]
    elif rp == "__init__":
        rp = ""
    return rp.replace("/", ".")


class FunctionInfo:
    """One top-level function or method: enough identity to resolve
    calls to it and summarize its body."""

    __slots__ = ("module", "qualname", "cls", "node", "ctx", "params")

    def __init__(self, module: str, qualname: str, cls: Optional[str],
                 node: ast.AST, ctx) -> None:
        self.module = module
        self.qualname = qualname       # "helper" or "Class.method"
        self.cls = cls                 # enclosing class name, or None
        self.node = node
        self.ctx = ctx
        a = node.args
        self.params: List[str] = [x.arg for x in
                                  list(a.posonlyargs) + list(a.args)]

    @property
    def key(self) -> str:
        return self.module + ":" + self.qualname

    def param_index(self, call: ast.Call, arg_node: ast.AST
                    ) -> Optional[int]:
        """Which parameter of this function a call-site argument lands
        on (positional by index — ``self`` shifts methods by one; a
        keyword by name). None when it cannot be told."""
        offset = 1 if self.cls is not None and self.params \
            and self.params[0] == "self" else 0
        for i, arg in enumerate(call.args):
            if arg is arg_node:
                idx = i + offset
                return idx if idx < len(self.params) else None
        for kw in call.keywords:
            if kw.value is arg_node and kw.arg in self.params:
                return self.params.index(kw.arg)
        return None


class ProjectIndex:
    """Symbol table over every file of one lint run. Single-file runs
    (``check_source``) get a one-file index, so intra-file
    cross-function findings behave identically in fixtures and in
    full-package scans."""

    def __init__(self, contexts) -> None:
        self.contexts = list(contexts)
        #: "module:qualname" -> FunctionInfo
        self.functions: Dict[str, FunctionInfo] = {}
        #: "module:name" -> module-level ast.Assign binding that name
        self.module_assigns: Dict[str, ast.Assign] = {}
        #: per-rule computed summaries (fixed points, call graphs)
        self.cache: Dict[str, object] = {}
        self._modules: List[str] = []
        for ctx in self.contexts:
            mod = module_name(ctx.relpath)
            ctx.module = mod
            ctx.project = self
            self._modules.append(mod)
            for stmt in ctx.tree.body:
                self._index(mod, ctx, stmt, cls=None)

    def _index(self, mod: str, ctx, stmt: ast.stmt,
               cls: Optional[str]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            qual = cls + "." + stmt.name if cls else stmt.name
            self.functions[mod + ":" + qual] = FunctionInfo(
                mod, qual, cls, stmt, ctx)
        elif isinstance(stmt, ast.ClassDef) and cls is None:
            for sub in stmt.body:
                self._index(mod, ctx, sub, cls=stmt.name)
        elif isinstance(stmt, ast.Assign) and cls is None:
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    self.module_assigns[mod + ":" + tgt.id] = stmt

    # -- resolution ----------------------------------------------------
    def _match_modules(self, modpath: str) -> Iterator[str]:
        for mod in self._modules:
            if mod == modpath or mod.endswith("." + modpath):
                yield mod

    def resolve_symbol(self, ctx, canon: Optional[str]
                       ) -> Optional[FunctionInfo]:
        """FunctionInfo for a canonical dotted name as seen from
        ``ctx`` (bare names look up the same module; dotted names
        suffix-match a module + top-level symbol)."""
        if not canon:
            return None
        if "." not in canon:
            return self.functions.get(ctx.module + ":" + canon)
        modpath, sym = canon.rsplit(".", 1)
        hits = [self.functions[m + ":" + sym]
                for m in self._match_modules(modpath)
                if m + ":" + sym in self.functions]
        return hits[0] if len(hits) == 1 else None

    def resolve_call(self, ctx, call: ast.Call,
                     cls: Optional[str] = None
                     ) -> Optional[FunctionInfo]:
        """FunctionInfo a call dispatches to, or None. ``cls`` is the
        enclosing class for ``self.method(...)`` resolution."""
        func = call.func
        if isinstance(func, ast.Attribute) \
                and isinstance(func.value, ast.Name) \
                and func.value.id == "self":
            if cls is not None:
                return self.functions.get(
                    ctx.module + ":" + cls + "." + func.attr)
            return None
        return self.resolve_symbol(ctx, ctx.canonical(func))

    def resolve_assign(self, ctx, canon: Optional[str]):
        """(module, name, ast.Assign) for a canonical dotted name that
        is a module-level binding somewhere in the project, or None."""
        if not canon:
            return None
        if "." not in canon:
            key = ctx.module + ":" + canon
            got = self.module_assigns.get(key)
            return (ctx.module, canon, got) if got is not None else None
        modpath, sym = canon.rsplit(".", 1)
        hits = [(m, sym, self.module_assigns[m + ":" + sym])
                for m in self._match_modules(modpath)
                if m + ":" + sym in self.module_assigns]
        return hits[0] if len(hits) == 1 else None

    def functions_in(self, ctx) -> Iterator[FunctionInfo]:
        for fi in self.functions.values():
            if fi.ctx is ctx:
                yield fi
