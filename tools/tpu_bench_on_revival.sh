#!/bin/bash
# Wait for the tunnel probe loop to succeed, then immediately run the
# staged bench and drop the JSON into the repo as evidence.
MARKER=/tmp/tpu_alive
LOG=/tmp/tpu_bench_on_revival.log
while [ ! -f "$MARKER" ]; do sleep 60; done
date +"%F %T tunnel alive - running bench" >> "$LOG"
cd /root/repo
BENCH_TIME_BUDGET=2400 timeout 4800 python bench.py \
  > /root/repo/TPU_BENCH_EVIDENCE.json 2>> "$LOG"
date +"%F %T bench done rc=$?" >> "$LOG"
