"""Per-phase wall-time breakdown of one boosting iteration.

Answers "where does the tree-build time go" on real hardware: gradient
computation, gh staging, root dispatch, whole-tree dispatch, record
read-back, score update — each fenced with block_until_ready so the
tunnel's async dispatch can't smear phases together. The phases are
recorded through the telemetry registry (lightgbm_tpu/obs) — the same
stage timer the trainer itself uses — so this tool is the registry's
hardware consumer, not a parallel hand-rolled timer. The reference's
equivalent is its per-tree timer dump (src/treelearner/
serial_tree_learner.cpp Global timer); here the phases map to the
mesh learner's actual dispatch structure (parallel/data_parallel.py
train()).

Usage:  python tools/tpu_phase_timer.py [rows] [n_trees]
Prints one JSON line per tree plus a summary (registry snapshot).

Fleet mode:  python tools/tpu_phase_timer.py --from-metrics DUMP|URL
Instead of running anything, read a metrics-gateway dump (a file, or a
gateway URL to scrape — see lightgbm_tpu/obs/gateway.py) and print the
per-rank phase table the fleet already reported: one JSON line per
rank with its ``stage_seconds_total``/``stage_calls_total`` breakdown,
plus a fleet summary (sources, push ages, run ids). This path parses
OpenMetrics with the stdlib-pure ``obs/openmetrics.py`` loaded by file
path and never imports jax.
"""
from __future__ import annotations

import json
import sys

sys.path.insert(0, __import__("os").path.join(
    __import__("os").path.dirname(__import__("os").path.abspath(__file__)),
    ".."))


def _from_metrics(src: str) -> None:
    """Per-rank stage table from a gateway metrics dump — must run
    BEFORE any jax import (the whole point of reading the dump is not
    needing the hardware this tool normally drives)."""
    import os
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import trace_report
    om = trace_report._openmetrics()
    text = trace_report.fetch_metrics_text(src)
    parsed = om.parse_openmetrics(text)
    pfx = om.kPrefix
    per_rank: dict = {}
    ages: dict = {}
    run_ids = set()
    for (name, labels), v in sorted(parsed.items()):
        ld = dict(labels)
        rank = str(ld.get("rank", "?"))
        if name == pfx + "stage_seconds_total":
            stage = per_rank.setdefault(rank, {}).setdefault(
                str(ld.get("stage", "?")), {"s": 0.0, "calls": 0})
            stage["s"] = round(stage["s"] + v, 4)
        elif name == pfx + "stage_calls_total":
            stage = per_rank.setdefault(rank, {}).setdefault(
                str(ld.get("stage", "?")), {"s": 0.0, "calls": 0})
            stage["calls"] = int(stage["calls"] + v)
        elif name == pfx + "gateway_push_age_seconds":
            ages["%s/%s" % (rank, ld.get("process", "?"))] = v
        elif name == pfx + "run_info" and ld.get("run_id"):
            run_ids.add(ld["run_id"])
    for rank in sorted(per_rank):
        print(json.dumps({"rank": rank, "phases": per_rank[rank]}),
              flush=True)
    print(json.dumps({"phase": "fleet", "source": src,
                      "ranks": len(per_rank),
                      "push_age_s": ages,
                      "run_ids": sorted(run_ids)}), flush=True)


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "--from-metrics":
        if len(sys.argv) != 3:
            print("usage: tpu_phase_timer.py --from-metrics DUMP|URL",
                  file=sys.stderr)
            raise SystemExit(2)
        _from_metrics(sys.argv[2])
        return
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    n_trees = int(sys.argv[2]) if len(sys.argv) > 2 else 3

    import jax
    import jax.numpy as jnp

    from bench import make_higgs_like, _enable_compile_cache
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset
    from lightgbm_tpu.boosting import create_boosting
    from lightgbm_tpu.obs import health as obs_health
    from lightgbm_tpu.obs.registry import registry

    _enable_compile_cache()
    registry.enable()
    obs_health.record_backend(source="tpu_phase_timer")
    print(json.dumps({"phase": "devices",
                      "platform": jax.devices()[0].platform}), flush=True)

    X, y = make_higgs_like(rows)
    cfg = Config.from_params({
        "objective": "binary", "num_leaves": 255, "max_bin": 255,
        "learning_rate": 0.1, "verbosity": -1, "min_data_in_leaf": 100,
        "tree_learner": "data", "mesh_shape": "data=1",
    })
    with registry.scope("phase::binned"):
        ds = BinnedDataset.from_matrix(X, cfg, label=y)
    print(json.dumps(
        {"phase": "binned",
         "s": round(registry.timer.totals["phase::binned"], 2)}),
        flush=True)
    del X

    booster = create_boosting(cfg, ds)
    learner = booster.learner
    objective = booster.objective

    # one full warmup iteration compiles everything
    with registry.scope("phase::warmup_iter"):
        booster.train_one_iter()
        jax.block_until_ready(booster.train_score)
    print(json.dumps(
        {"phase": "warmup_iter",
         "s": round(registry.timer.totals["phase::warmup_iter"], 2)}),
        flush=True)

    def fenced(name, fn):
        """Run fn under a registry stage scope with a device fence so
        the async dispatch cost lands in ITS stage."""
        with registry.scope(name):
            out = fn()
            jax.block_until_ready(out)
        return out

    PHASES = ("phase::grad", "phase::stage_gh", "phase::root_fn",
              "phase::tree_fn", "phase::readback")
    for k in range(n_trees):
        before = {p: registry.timer.totals.get(p, 0.0) for p in PHASES}
        # same call shape as GBDT.train_one_iter (boosting/gbdt.py)
        grad, hess = fenced("phase::grad", lambda: objective.get_gradients(
            booster.train_score[:, 0]))
        gh = fenced("phase::stage_gh",
                    lambda: learner._make_gh(grad, hess, None))
        feature_mask = learner._sample_features()
        state, root_rec = fenced("phase::root_fn", lambda: learner._root_fn(
            learner.bins, gh, feature_mask, jnp.int32(k + 1),
            learner._qscale))
        state, recs = fenced("phase::tree_fn", lambda: learner._tree_fn(
            learner.bins, state, feature_mask, jnp.int32(k + 1),
            learner._qscale))
        with registry.scope("phase::readback"):
            jax.device_get(recs)

        rec = {p.split("::", 1)[1]:
               round(registry.timer.totals.get(p, 0.0) - before[p], 4)
               for p in PHASES}
        rec["tree"] = k
        print(json.dumps(rec), flush=True)

    summary = {p.split("::", 1)[1]:
               round(registry.timer.totals.get(p, 0.0) / n_trees, 4)
               for p in PHASES}
    summary["phase"] = "mean_per_tree"
    summary["rows"] = rows
    summary["registry"] = registry.snapshot()
    print(json.dumps(summary), flush=True)


if __name__ == "__main__":
    main()
