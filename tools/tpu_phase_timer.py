"""Per-phase wall-time breakdown of one boosting iteration.

Answers "where does the tree-build time go" on real hardware: gradient
computation, gh staging, root dispatch, whole-tree dispatch, record
read-back, score update — each fenced with block_until_ready so the
tunnel's async dispatch can't smear phases together. The reference's
equivalent is its per-tree timer dump (src/treelearner/
serial_tree_learner.cpp Global timer); here the phases map to the
mesh learner's actual dispatch structure (parallel/data_parallel.py
train()).

Usage:  python tools/tpu_phase_timer.py [rows] [n_trees]
Prints one JSON line per tree plus a summary.
"""
from __future__ import annotations

import json
import sys
import time

sys.path.insert(0, __import__("os").path.join(
    __import__("os").path.dirname(__import__("os").path.abspath(__file__)),
    ".."))


def main() -> None:
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    n_trees = int(sys.argv[2]) if len(sys.argv) > 2 else 3

    import jax
    import jax.numpy as jnp

    from bench import make_higgs_like, _enable_compile_cache
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset
    from lightgbm_tpu.boosting import create_boosting

    _enable_compile_cache()
    print(json.dumps({"phase": "devices",
                      "platform": jax.devices()[0].platform}), flush=True)

    X, y = make_higgs_like(rows)
    cfg = Config.from_params({
        "objective": "binary", "num_leaves": 255, "max_bin": 255,
        "learning_rate": 0.1, "verbosity": -1, "min_data_in_leaf": 100,
        "tree_learner": "data", "mesh_shape": "data=1",
    })
    t0 = time.time()
    ds = BinnedDataset.from_matrix(X, cfg, label=y)
    print(json.dumps({"phase": "binned", "s": round(time.time() - t0, 2)}),
          flush=True)
    del X

    booster = create_boosting(cfg, ds)
    learner = booster.learner
    objective = booster.objective

    # one full warmup iteration compiles everything
    t0 = time.time()
    booster.train_one_iter()
    jax.block_until_ready(booster.train_score)
    print(json.dumps({"phase": "warmup_iter",
                      "s": round(time.time() - t0, 2)}), flush=True)

    def fence(x):
        jax.block_until_ready(x)
        return time.time()

    totals: dict = {}
    for k in range(n_trees):
        rec = {}
        t = time.time()
        # same call shape as GBDT.train_one_iter (boosting/gbdt.py:293)
        grad, hess = objective.get_gradients(booster.train_score[:, 0])
        t2 = fence((grad, hess))
        rec["grad"] = t2 - t

        t = t2
        gh = learner._make_gh(grad, hess, None)
        t2 = fence(gh)
        rec["stage_gh"] = t2 - t

        t = t2
        feature_mask = learner._sample_features()
        state, root_rec = learner._root_fn(learner.bins, gh, feature_mask,
                                           jnp.int32(k + 1))
        t2 = fence(root_rec)
        rec["root_fn"] = t2 - t

        t = t2
        state, recs = learner._tree_fn(learner.bins, state, feature_mask,
                                       jnp.int32(k + 1))
        t2 = fence(recs)
        rec["tree_fn"] = t2 - t

        t = t2
        jax.device_get(recs)
        t2 = time.time()
        rec["readback"] = t2 - t

        rec = {k2: round(v, 4) for k2, v in rec.items()}
        rec["tree"] = k
        print(json.dumps(rec), flush=True)
        for k2, v in rec.items():
            if isinstance(v, float):
                totals[k2] = totals.get(k2, 0.0) + v

    summary = {k2: round(v / n_trees, 4) for k2, v in totals.items()}
    summary["phase"] = "mean_per_tree"
    summary["rows"] = rows
    print(json.dumps(summary), flush=True)


if __name__ == "__main__":
    main()
