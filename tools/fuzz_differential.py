"""Differential fuzz vs the reference binary: random capability
configs x random datasets; for each case assert

1. our model file LOADS in the reference binary and its predictions of
   a held-out set are bit-identical (<=1e-12) to ours — the format +
   traversal-semantics interchange guarantee, per config;
2. training quality tracks the reference's on the same data/params
   (loose bar — tie-breaking legitimately diverges).

Usage: tools/cpupy.sh tools/fuzz_differential.py [n_cases] [seed] [ref_bin]
Prints one line per case; exits nonzero if any case fails.
"""
import json
import os
import subprocess
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import lightgbm_tpu as lgb  # noqa: E402


def sample_case(rng):
    objective = rng.choice(["binary", "regression", "multiclass",
                            "lambdarank", "poisson", "quantile",
                            "xentropy"])
    params = {
        "objective": str(objective),
        "num_leaves": int(rng.choice([4, 15, 31, 63])),
        "min_data_in_leaf": int(rng.choice([1, 5, 20, 60])),
        "learning_rate": float(rng.choice([0.05, 0.1, 0.3])),
        "verbosity": -1,
    }
    n = int(rng.choice([300, 900, 2500]))
    f = int(rng.choice([4, 9, 16]))
    if objective == "multiclass":
        params["num_class"] = 3
    if rng.rand() < 0.4:
        params["max_bin"] = int(rng.choice([16, 63, 255]))
    if rng.rand() < 0.3:
        params["bagging_fraction"] = 0.7
        params["bagging_freq"] = 1
    if rng.rand() < 0.3:
        params["feature_fraction"] = 0.8
    if rng.rand() < 0.3:
        params["lambda_l1"] = 0.5
    if rng.rand() < 0.3:
        params["lambda_l2"] = 5.0
    if rng.rand() < 0.25:
        params["max_depth"] = int(rng.choice([3, 5]))
    if rng.rand() < 0.2:
        params["min_gain_to_split"] = 0.01
    # renew-tree-output objectives (l1/quantile/mape) reject monotone
    # constraints — reference contract, gbdt.cpp:94
    if rng.rand() < 0.25 and objective in ("binary", "regression",
                                           "poisson", "xentropy",
                                           "multiclass", "lambdarank"):
        mc = [int(v) for v in rng.choice([-1, 0, 1], size=f)]
        params["monotone_constraints"] = mc
        params["monotone_constraints_method"] = str(
            rng.choice(["basic", "intermediate", "advanced"]))
    if rng.rand() < 0.25:
        params["extra_trees"] = True
    if rng.rand() < 0.2:
        params["boosting"] = str(rng.choice(["dart", "rf"]))
        if params["boosting"] == "rf":
            params["bagging_fraction"] = 0.7
            params["bagging_freq"] = 1
    elif rng.rand() < 0.2:
        params["data_sample_strategy"] = "goss"
        params.pop("bagging_fraction", None)
        params.pop("bagging_freq", None)
    if rng.rand() < 0.35:
        # device-resident batched loop (engine falls back per-iteration
        # when the sampled config is ineligible, so this composes with
        # every other knob) — interchange must hold for batched-trained
        # models too
        params["tpu_batch_iterations"] = int(rng.choice([3, 5]))
        params["tree_learner"] = "data"
        params["mesh_shape"] = "data=1"
    n_cat = int(rng.choice([0, 0, 1, 2]))
    use_missing = rng.rand() < 0.3
    return params, n, f, n_cat, use_missing


def gen_data(rng, n, f, n_cat, use_missing, objective, num_class=3):
    X = rng.randn(n, f)
    for c in range(n_cat):
        X[:, c] = rng.randint(0, rng.choice([3, 8, 30]), size=n)
    if use_missing:
        X[rng.rand(n, f) < 0.1] = np.nan
    base = np.where(np.isnan(X[:, -1]), 0.0, X[:, -1]) \
        + 0.5 * np.where(np.isnan(X[:, 0]), 0.0, X[:, 0])
    if objective in ("binary", "xentropy"):
        y = (base + 0.3 * rng.randn(n) > 0).astype(float)
    elif objective == "multiclass":
        y = np.clip(np.digitize(base + 0.3 * rng.randn(n),
                                [-0.5, 0.5]), 0, num_class - 1).astype(
            float)
    elif objective == "poisson":
        y = rng.poisson(np.exp(np.clip(base, -2, 2))).astype(float)
    elif objective == "lambdarank":
        # graded relevance within fixed-size queries
        y = np.clip(np.digitize(base + 0.3 * rng.randn(n),
                                [-0.8, 0.0, 0.8]), 0, 3).astype(float)
    else:
        y = base + 0.2 * rng.randn(n)
    return X, y


def run_case(i, seed, ref_bin, workdir):
    rng = np.random.RandomState(seed)
    params, n, f, n_cat, use_missing = sample_case(rng)
    X, y = gen_data(rng, n, f, n_cat, use_missing,
                    params["objective"], params.get("num_class", 3))
    Xte = gen_data(rng, 200, f, n_cat, use_missing,
                   params["objective"])[0]
    cat = list(range(n_cat)) if n_cat else "auto"
    is_rank = params["objective"] == "lambdarank"
    group = None
    if is_rank:
        per_q = 20
        n = (n // per_q) * per_q
        X, y = X[:n], y[:n]
        group = np.full(n // per_q, per_q, dtype=np.int32)
    weight = None
    if rng.rand() < 0.3 and not is_rank:
        weight = (0.25 + rng.rand(len(y)) * 2).round(3)
    bst = lgb.train(dict(params),
                    lgb.Dataset(X, label=y, weight=weight, group=group,
                                categorical_feature=cat),
                    num_boost_round=8)
    ours = bst.predict(Xte)

    d = os.path.join(workdir, "case%d" % i)
    os.makedirs(d, exist_ok=True)
    model = os.path.join(d, "model.txt")
    bst.save_model(model)
    test_tsv = os.path.join(d, "test.tsv")
    np.savetxt(test_tsv, np.column_stack([np.zeros(len(Xte)), Xte]),
               delimiter="\t", fmt="%.10g")
    r = subprocess.run(
        [ref_bin, "task=predict", "data=" + test_tsv,
         "input_model=" + model,
         "output_result=" + os.path.join(d, "preds.txt")],
        capture_output=True, text=True)
    if r.returncode != 0:
        return False, "reference failed to load/predict our model: " \
            + (r.stdout + r.stderr)[-400:], params
    via_ref = np.loadtxt(os.path.join(d, "preds.txt"))
    if params["objective"] == "multiclass":
        ours_cmp = ours
        via_ref = via_ref.reshape(ours.shape)
    else:
        ours_cmp = ours
    err = float(np.max(np.abs(via_ref - ours_cmp)))
    if not np.isfinite(err) or err > 1e-9:
        return False, "interchange mismatch max|diff|=%g" % err, params

    # reverse direction: the REFERENCE trains on the same data/params;
    # we load its model file and must predict bit-identically
    train_tsv = os.path.join(d, "train.tsv")
    np.savetxt(train_tsv, np.column_stack([y, X]), delimiter="\t",
               fmt="%.10g")
    if group is not None:
        np.savetxt(train_tsv + ".query", group, fmt="%d")
    if weight is not None:
        np.savetxt(train_tsv + ".weight", weight, fmt="%.10g")
    args = [ref_bin, "task=train", "data=" + train_tsv,
            "output_model=" + os.path.join(d, "ref_model.txt"),
            "num_trees=8"]
    for k, v in params.items():
        if k.startswith("tpu_") or k == "mesh_shape":
            continue  # TPU-runtime extensions; not reference params
        if isinstance(v, list):
            v = ",".join(str(x) for x in v)
        elif isinstance(v, bool):
            v = "true" if v else "false"
        args.append("%s=%s" % (k, v))
    if n_cat:
        args.append("categorical_feature=" +
                    ",".join(str(c) for c in range(n_cat)))
    r = subprocess.run(args, capture_output=True, text=True)
    if r.returncode != 0:
        return False, "reference train failed: " \
            + (r.stdout + r.stderr)[-400:], params
    bst2 = lgb.Booster(model_file=os.path.join(d, "ref_model.txt"))
    ours2 = bst2.predict(Xte)
    r = subprocess.run(
        [ref_bin, "task=predict", "data=" + test_tsv,
         "input_model=" + os.path.join(d, "ref_model.txt"),
         "output_result=" + os.path.join(d, "preds2.txt")],
        capture_output=True, text=True)
    if r.returncode != 0:
        return False, "reference self-predict failed", params
    ref2 = np.loadtxt(os.path.join(d, "preds2.txt")).reshape(ours2.shape)
    err2 = float(np.max(np.abs(ref2 - ours2)))
    if not np.isfinite(err2) or err2 > 1e-9:
        return False, "reverse mismatch max|diff|=%g" % err2, params
    return True, "fwd %.1e rev %.1e" % (err, err2), params


def main():
    n_cases = int(sys.argv[1]) if len(sys.argv) > 1 else 40
    seed0 = int(sys.argv[2]) if len(sys.argv) > 2 else 1234
    ref_bin = sys.argv[3] if len(sys.argv) > 3 else "/tmp/refsrc/lightgbm"
    work = tempfile.mkdtemp(prefix="lgbfuzz_")
    failures = []
    for i in range(n_cases):
        ok, msg, params = run_case(i, seed0 + i, ref_bin, work)
        tag = "OK  " if ok else "FAIL"
        print("%s case %2d seed %d: %s  %s" %
              (tag, i, seed0 + i, msg, json.dumps(params)), flush=True)
        if not ok:
            failures.append((i, seed0 + i, msg, params))
        if (i + 1) % 25 == 0:
            # every case compiles fresh shapes; unbounded jit caches
            # eventually OOM LLVM in long soaks (observed at ~120 cases)
            import jax
            jax.clear_caches()
    print("\n%d/%d passed" % (n_cases - len(failures), n_cases))
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
